"""Abacus: per-tenant resource metering and cost attribution.

Causeway (obs/trace.py) says *where time went*; Skyline (obs/capacity)
says *how many replicas a load needs*; nothing below this module says
*who consumed the machine*. Mosaic tenants share prefix blocks, LoRA
banks, and DRR admission with zero accounting of the FLOPs, KV
residency, or wire bytes each tenant actually burned — and production
TPU serving is ultimately judged in cost-per-token. This module is the
ledger: every unit of consumption is attributed to a (tenant, request)
pair at choke points the repo already owns, and nowhere else.

What gets billed, and where the hook sits:

- **FLOPs** — analytic counts (:func:`utils.flops.fwd_flops` at batch
  1, seq 1, cached per engine) at the :class:`serve.engine
  .ServingEngine` round boundaries: prefill bills ``suffix_tokens x
  flops_per_token`` per admission, each decode round bills one token
  per active slot, split per-slot by tenant. Cached-prefix tokens the
  engine did NOT recompute are credited as *savings* (``saved_flops``
  / ``saved_tokens``) from the PrefixCache hit the admission carried.
- **KV block-seconds** — settled on every :class:`serve.kv_pool
  .KVPool` mutation (reserve/free/adopt/evict): the elapsed interval
  is charged to every resident block, refcount-weighted — a block
  shared by 5 tenants bills 1/5 to each (integer microseconds,
  largest-remainder split, so the per-tenant charges sum EXACTLY to
  the wall-clock block-seconds — the conservation property
  tests/test_meter.py drills). Cached-ring blocks bill fully to the
  tenant that donated them (streamed-in blocks to ``"-"``).
- **Wire bytes** — the :func:`ops.collectives._record` fan-out
  (collective payloads, unattributed ``"-"``) and ``kv_transfer``
  (billed to the riding tenant the disagg fleet threads through).
- **Queue / decode wall-seconds + tokens** — from the lifecycle
  timestamps the engine already computes per finished request.

Ledger values are INTEGERS (flops, microseconds, bytes, tokens):
per-tenant ledgers sum to the global totals exactly, with no float
associativity caveats — the ``scripts/obs_cost.py --selftest``
acceptance gate.

Arming: ``TPUNN_METER=`` (chaos-style spec grammar):

    TPUNN_METER=1                 # defaults
    TPUNN_METER=max_tenants=64    # ledger bound (overflow bills "-")

Design contract (the chaos/watchtower/trace lint rules, enforced by
tests/test_quality.py):

- **Inert when unset.** Every ``on_*`` hook opens with the literal
  ``if _meter is None: return`` — an unset ``TPUNN_METER`` costs one
  global load + one comparison per hook and performs ZERO registry or
  flight-ring writes (instruments are registered at arm time).
- **Emit-first.** Every billing lands in the flight ring before the
  ledger or the registry sees it (:meth:`Meter._account`'s first
  statement).
- **One choke point.** ALL billing flows through ``Meter._account``
  (the ``_transition``/``_score``/``_account`` pattern): no ledger
  field or meter counter moves anywhere else.

Cross-process: ledgers publish at ``meter/<rank>`` over the native
store (:func:`obs.aggregate.publish_ledgers`) so ProcessFleet and the
disagg fleet roll up fleet-wide; a disagg request bills its prefill
leg and its decode leg to the same tenant across the handoff (the
fleet threads ``tenant=`` through both legs).

Stdlib-only (no jax, no numpy): ``fleet_worker.py`` imports this
before deciding whether to touch a backend.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time

from pytorch_distributed_nn_tpu.obs import audit, flight
from pytorch_distributed_nn_tpu.obs.registry import get_registry

log = logging.getLogger(__name__)

ENV_METER = "TPUNN_METER"

# every ledger field, all integers: flops (analytic), saved_flops /
# saved_tokens (prefix-cache credit), kv_block_us (refcount-weighted
# residency, microseconds), wire_bytes, queue_us / decode_us
# (lifecycle wall time), tokens, requests
LEDGER_FIELDS = ("flops", "saved_flops", "tokens", "saved_tokens",
                 "kv_block_us", "wire_bytes", "queue_us", "decode_us",
                 "requests")

# the unattributed bucket: training collectives, streamed-in cache
# warmth, and ledger overflow past max_tenants all bill here — the
# machine's overhead line, never silently dropped
UNATTRIBUTED = "-"


@dataclasses.dataclass
class MeterConfig:
    """``TPUNN_METER`` spec knobs (chaos-grammar ``key=value:...``)."""

    max_tenants: int = 256  # ledger bound; overflow bills UNATTRIBUTED


_FIELD_TYPES = {f.name: f.type for f in dataclasses.fields(MeterConfig)}


def parse_spec(spec: str) -> MeterConfig:
    """``TPUNN_METER`` spec → :class:`MeterConfig`. ``"1"`` / ``"on"``
    mean defaults; otherwise ``:``-separated ``key=value`` overrides.
    Unknown keys raise (a typo'd meter spec must fail loudly, not
    silently bill nothing — the chaos-spec contract)."""
    cfg = MeterConfig()
    spec = (spec or "").strip()
    if spec in ("", "1", "on", "true"):
        return cfg
    for field in filter(None, spec.split(":")):
        key, eq, value = field.partition("=")
        key = key.strip()
        if not eq or key not in _FIELD_TYPES:
            raise ValueError(
                f"unknown meter key {key!r} in {spec!r}; have "
                f"{sorted(_FIELD_TYPES)}")
        try:
            kind = _FIELD_TYPES[key]
            setattr(cfg, key,
                    value if kind in (str, "str")
                    else int(value) if kind in (int, "int")
                    else float(value))
        except ValueError:
            raise ValueError(
                f"bad value for meter key {key!r}: {value!r}") from None
    if cfg.max_tenants < 1:
        raise ValueError(
            f"max_tenants must be >= 1, got {cfg.max_tenants}")
    return cfg


def merge_ledgers(parts) -> dict[str, dict[str, int]]:
    """Sum per-tenant integer ledgers across processes/ranks — the
    fleet rollup (and the exactness contract: integer addition is
    associative, so any merge order yields identical totals)."""
    out: dict[str, dict[str, int]] = {}
    for ledgers in parts:
        for tenant, led in ledgers.items():
            dst = out.setdefault(str(tenant),
                                 dict.fromkeys(LEDGER_FIELDS, 0))
            for k in LEDGER_FIELDS:
                dst[k] += int(led.get(k, 0))
    return {t: out[t] for t in sorted(out)}


def ledger_totals(ledgers: dict[str, dict[str, int]]) -> dict[str, int]:
    """Global totals = the exact sum of the per-tenant rows."""
    totals = dict.fromkeys(LEDGER_FIELDS, 0)
    for led in ledgers.values():
        for k in LEDGER_FIELDS:
            totals[k] += int(led.get(k, 0))
    return totals


class Meter:
    """Per-process billing engine. One instance per armed process
    (module singleton); an in-process fleet's engines all bill the same
    meter, and the store transport joins worker processes' ledgers."""

    def __init__(self, config: MeterConfig, *, rank: int = 0,
                 metrics=None) -> None:
        self.cfg = config
        self.rank = int(rank)
        self.metrics = metrics  # MetricsLogger | None
        self._lock = threading.Lock()
        # tenant -> {field: int} — the product of this module
        self.ledgers: dict[str, dict[str, int]] = {}
        # KV residency model (settle-on-event):
        #   block -> live sharer seq_ids (refcount-weighted split)
        self._block_seqs: dict[int, set[str]] = {}
        #   seq -> its reserved block table (reserve-time snapshot)
        self._seq_blocks: dict[str, tuple[int, ...]] = {}
        #   seq -> tenant (bound at the scheduler's QUEUED transition)
        self._seq_tenant: dict[str, str] = {}
        #   cached-ring block -> donating tenant
        self._cached_owner: dict[int, str] = {}
        # injectable clock (tests drive it): seconds, monotonic
        self._clock = time.monotonic
        self._last_us = self._now_us()
        # independent conservation witness: sum over settles of
        # dt x resident_blocks — the per-tenant kv_block_us charges
        # must sum to this EXACTLY (tests/test_meter.py)
        self._kv_wall_us = 0
        self._accounts = 0   # _account call count (publish dedup)
        self._published = 0
        # registered HERE, not at import: TPUNN_METER unset must mean
        # zero registry writes (tested)
        reg = get_registry()
        self._c_flops = reg.counter(
            "meter_flops_total", "analytic FLOPs billed per tenant",
            labels=("tenant",))
        self._c_kvsec = reg.counter(
            "meter_kv_block_seconds",
            "refcount-weighted KV block residency per tenant",
            labels=("tenant",))
        self._c_wire = reg.counter(
            "meter_wire_bytes_total",
            "collective/kv_transfer wire bytes billed per tenant",
            labels=("tenant",))

    # -- clock -------------------------------------------------------------

    def _now_us(self) -> int:
        return int(self._clock() * 1e6)

    # -- the single billing choke point ------------------------------------

    def _account(self, kind: str, tenant: str, amount: int) -> None:
        """EVERY billing funnels through here (lint-enforced): flight
        ring first (a crash right after a charge must still show it
        post-mortem), then the ledger, then the registry counters.
        Caller holds the lock."""
        flight.record("meter", kind, nbytes=int(amount),
                      note=f"{tenant}:{amount}")
        amount = int(amount)
        if tenant not in self.ledgers \
                and len(self.ledgers) >= self.cfg.max_tenants:
            tenant = UNATTRIBUTED
        led = self.ledgers.get(tenant)
        if led is None:
            led = self.ledgers[tenant] = dict.fromkeys(LEDGER_FIELDS, 0)
        led[kind] += amount
        self._accounts += 1
        if kind == "flops":
            self._c_flops.inc(amount, tenant=tenant)
        elif kind == "kv_block_us":
            self._c_kvsec.inc(amount / 1e6, tenant=tenant)
        elif kind == "wire_bytes":
            self._c_wire.inc(amount, tenant=tenant)

    # -- KV residency (settle-on-event) ------------------------------------

    def _settle(self) -> None:
        """Charge the interval since the last pool event to every
        resident block: live blocks split across their sharers'
        tenants by largest-remainder integer division (a block shared
        k ways bills dt/k each, remainders to the first sharers in
        sorted order — the charges sum to dt EXACTLY); cached blocks
        bill fully to their donating owner. Caller holds the lock."""
        now = self._now_us()
        dt = now - self._last_us
        self._last_us = now
        if dt <= 0 or not (self._block_seqs or self._cached_owner):
            return
        charges: dict[str, int] = {}
        for seqs in self._block_seqs.values():
            per, rem = divmod(dt, len(seqs))
            for i, seq in enumerate(sorted(seqs)):
                c = per + (1 if i < rem else 0)
                if c:
                    t = self._seq_tenant.get(seq, UNATTRIBUTED)
                    charges[t] = charges.get(t, 0) + c
            self._kv_wall_us += dt
        for owner in self._cached_owner.values():
            charges[owner] = charges.get(owner, 0) + dt
            self._kv_wall_us += dt
        for tenant in sorted(charges):
            self._account("kv_block_us", tenant, charges[tenant])

    # -- billing entry points (engine/scheduler/pool/wire hooks call
    #    these through the module-level inert wrappers) ---------------------

    def request_state(self, request_id: str, tenant: str,
                      state: str) -> None:
        """Scheduler ``_transition`` feed: QUEUED binds the tenant the
        later pool reservations bill to; a terminal state on a request
        that never reserved drops the binding (bounded memory)."""
        with self._lock:
            if state == "queued":
                self._seq_tenant[request_id] = str(tenant)
            elif state in ("done", "rejected", "failed") \
                    and request_id not in self._seq_blocks:
                self._seq_tenant.pop(request_id, None)

    def prefill(self, request_id: str, tenant: str, *, new_tokens: int,
                cached_tokens: int, flops_per_token: int) -> None:
        with self._lock:
            if flops_per_token > 0 and new_tokens > 0:
                self._account("flops", tenant,
                              new_tokens * flops_per_token)
            if cached_tokens > 0:
                self._account("saved_tokens", tenant, cached_tokens)
                if flops_per_token > 0:
                    self._account("saved_flops", tenant,
                                  cached_tokens * flops_per_token)

    def decode_round(self, slot_tenants, flops_per_token: int) -> None:
        """One decode round: every active slot produced one token —
        bill each tenant its slot count x flops_per_token."""
        if flops_per_token <= 0:
            return
        counts: dict[str, int] = {}
        for tenant in slot_tenants:
            counts[tenant] = counts.get(tenant, 0) + 1
        with self._lock:
            for tenant in sorted(counts):
                self._account("flops", tenant,
                              counts[tenant] * flops_per_token)

    def request_done(self, rec: dict, flops_per_token: int) -> None:
        """A finished request's lifecycle charges (from the engine's
        serve_request record) + the cost-anomaly feed."""
        tenant = str(rec.get("tenant", "default"))
        new = int(rec.get("new_tokens", 0))
        wf = rec.get("waterfall", {})
        queue_us = int(round(float(wf.get("queued_s", 0.0)) * 1e6))
        decode_us = int(round(float(wf.get("decode_s", 0.0)) * 1e6))
        with self._lock:
            self._account("requests", tenant, 1)
            if new:
                self._account("tokens", tenant, new)
            if queue_us:
                self._account("queue_us", tenant, queue_us)
            if decode_us:
                self._account("decode_us", tenant, decode_us)
        if self.metrics is not None:
            self.metrics.emit(
                "meter_request", tenant=tenant,
                request_id=str(rec.get("request_id", "")),
                tokens=new, flops=self._request_flops(rec,
                                                      flops_per_token))
        if flops_per_token > 0 and new > 0:
            # per-request billed-FLOPs-per-token: the cost-anomaly
            # detector's signal (unpriced proxy — a tenant whose cache
            # hit-rate collapses pages before the bill does). Lazy
            # import: watchtower never imports meter, so no cycle.
            from pytorch_distributed_nn_tpu.obs import watchtower

            watchtower.on_tenant_cost(
                tenant,
                self._request_flops(rec, flops_per_token) / new,
                request_id=str(rec.get("request_id", "")))

    @staticmethod
    def _request_flops(rec: dict, flops_per_token: int) -> int:
        """The analytic per-request total the round-boundary billing
        sums to: (prompt suffix actually prefilled) + (decode rounds =
        new_tokens - 1, the first token being prefill's)."""
        prefilled = (int(rec.get("prompt_len", 0))
                     - int(rec.get("cached_tokens", 0)))
        decoded = max(int(rec.get("new_tokens", 0)) - 1, 0)
        return max(prefilled + decoded, 0) * int(flops_per_token)

    def kv_reserve(self, seq_id: str, blocks) -> None:
        with self._lock:
            self._settle()
            for b in blocks:
                # a cached block promoted to live leaves the donor's
                # meter and starts splitting across its sharers
                self._cached_owner.pop(b, None)
                self._block_seqs.setdefault(int(b), set()).add(seq_id)
            self._seq_blocks[seq_id] = tuple(int(b) for b in blocks)

    def kv_free(self, seq_id: str, cached=()) -> None:
        """``cached`` names the blocks the pool parked in the LRU ring
        (the donation): they keep billing, to the donating tenant."""
        with self._lock:
            self._settle()
            owner = self._seq_tenant.pop(seq_id, UNATTRIBUTED)
            parked = {int(b) for b in cached}
            for b in self._seq_blocks.pop(seq_id, ()):
                seqs = self._block_seqs.get(b)
                if seqs is None:
                    continue
                seqs.discard(seq_id)
                if not seqs:
                    del self._block_seqs[b]
                    if b in parked:
                        self._cached_owner[b] = owner
            # a parked block shared with a still-live sequence stays in
            # _block_seqs above; any parked block we never tracked
            # (bare-pool edge) still bills, unattributed
            for b in parked:
                if b not in self._block_seqs \
                        and b not in self._cached_owner:
                    self._cached_owner[b] = owner

    def kv_adopt(self, block: int) -> None:
        """A streamed-in peer block parked in the cached ring: real
        residency with no local donor — bills unattributed."""
        with self._lock:
            self._settle()
            self._cached_owner[int(block)] = UNATTRIBUTED

    def kv_evict(self, block: int) -> None:
        with self._lock:
            self._settle()
            self._cached_owner.pop(int(block), None)

    def wire(self, nbytes: int, tenant: str = UNATTRIBUTED) -> None:
        if nbytes <= 0:
            return
        with self._lock:
            self._account("wire_bytes", tenant or UNATTRIBUTED,
                          int(nbytes))

    # -- export ------------------------------------------------------------

    def export_ledgers(self) -> dict[str, dict[str, int]]:
        """Settle outstanding KV residency, then a sorted deep copy —
        the canonical (JSON-stable) per-tenant view."""
        with self._lock:
            self._settle()
            return {t: dict(self.ledgers[t])
                    for t in sorted(self.ledgers)}

    def summary(self) -> dict:
        ledgers = self.export_ledgers()
        return {"tenants": ledgers,
                "totals": ledger_totals(ledgers),
                "kv_wall_us": self._kv_wall_us,
                "rank": self.rank}

    def emit_ledgers(self) -> None:
        """One ``meter_ledger`` JSONL record per tenant (last-wins in
        the stream): the feed ``scripts/obs_cost.py`` and the Abacus
        report section read back from a runs dir."""
        if self.metrics is None:
            return
        for tenant, led in self.export_ledgers().items():
            self.metrics.emit("meter_ledger", tenant=tenant, **led)


# ---------------------------------------------------------------------------
# Module singleton + the inert hooks (chaos-style lint contract)
# ---------------------------------------------------------------------------

_meter: Meter | None = None


def maybe_init(spec: str | None = None, *, rank: int | None = None,
               metrics=None,
               config: MeterConfig | None = None) -> Meter | None:
    """Arm the process meter from ``TPUNN_METER`` (or an explicit
    ``spec``/``config``). No-op beyond one env read when unset or
    ``"0"``; idempotent when armed."""
    global _meter
    if _meter is not None:
        return _meter
    spec = os.environ.get(ENV_METER) if spec is None else spec
    if not spec or spec == "0":
        return None
    _meter = Meter(
        config if config is not None else parse_spec(spec),
        rank=flight.default_rank() if rank is None else rank,
        metrics=metrics,
    )
    log.warning("meter armed: %s (rank %d)", spec, _meter.rank)
    return _meter


def enabled() -> bool:
    return _meter is not None


def meter() -> Meter | None:
    return _meter


def reset() -> None:
    """Disarm (test isolation)."""
    global _meter
    _meter = None


def attach_metrics(metrics) -> None:
    """Late-bind the JSONL sink (engines/fleets construct after
    arming). Not a hot-path hook, but still inert-guarded."""
    if _meter is None:
        return
    if metrics is not None:
        _meter.metrics = metrics


def export_ledgers() -> dict[str, dict[str, int]]:
    """This process's per-tenant ledgers; {} when unarmed."""
    if _meter is None:
        return {}
    return _meter.export_ledgers()


def summary() -> dict | None:
    """Ledgers + exact totals + the KV conservation witness; None when
    unarmed (consumers key their sections off the None)."""
    if _meter is None:
        return None
    return _meter.summary()


# -- billing hooks (every one: inert fast path, lint-enforced) --------------


def on_request_state(request_id: str, tenant: str, state: str) -> None:
    """Scheduler ``_transition`` feed (lint-pinned to that one choke
    point): binds seq -> tenant before any pool reservation bills.
    Lighthouse shadow/probe legs (the reserved audit tenant) are
    duplicates of already-billed traffic and never enter a ledger."""
    if _meter is None:
        return
    if tenant == audit.SHADOW_TENANT:
        return
    _meter.request_state(request_id, tenant, state)


def on_prefill(request_id: str, tenant: str, *, new_tokens: int,
               cached_tokens: int, flops_per_token: int) -> None:
    """Engine admission: bill the prefilled suffix, credit the cached
    prefix the PrefixCache hit skipped."""
    if _meter is None:
        return
    _meter.prefill(request_id, tenant, new_tokens=new_tokens,
                   cached_tokens=cached_tokens,
                   flops_per_token=flops_per_token)


def on_decode_round(slot_tenants, flops_per_token: int) -> None:
    """Engine round boundary (called from ``step()``, never from the
    ``_decode_round`` hot loop — its lint bans extras): one token per
    active slot, split by tenant."""
    if _meter is None:
        return
    _meter.decode_round(slot_tenants, flops_per_token)


def on_request_done(rec: dict, flops_per_token: int = 0) -> None:
    """Engine ``_finish_record`` feed: lifecycle wall time, tokens,
    the per-request JSONL record, and the cost-anomaly signal."""
    if _meter is None:
        return
    _meter.request_done(rec, flops_per_token)


def on_kv_reserve(seq_id: str, blocks) -> None:
    """KVPool ``reserve`` succeeded: ``blocks`` is the sequence's full
    table (shared prefix blocks + fresh)."""
    if _meter is None:
        return
    _meter.kv_reserve(seq_id, blocks)


def on_kv_free(seq_id: str, cached=()) -> None:
    """KVPool ``free``: the sequence's residency ends; ``cached``
    blocks were donated to the LRU ring and keep billing the donor."""
    if _meter is None:
        return
    _meter.kv_free(seq_id, cached)


def on_kv_adopt(block: int) -> None:
    """KVPool ``adopt_cached``: a streamed-in block starts billing."""
    if _meter is None:
        return
    _meter.kv_adopt(block)


def on_kv_evict(block: int) -> None:
    """KVPool ``release_cached``: a cached block's residency ends."""
    if _meter is None:
        return
    _meter.kv_evict(block)


def on_collective(op: str, nbytes: int) -> None:
    """``ops.collectives._record`` fan-out: collective payload bytes,
    billed to the unattributed bucket (no request rides a psum)."""
    if _meter is None:
        return
    _meter.wire(nbytes)


def on_transfer(nbytes: int, tenant: str = "") -> None:
    """``ops.collectives.kv_transfer`` wire point: streamed KV bytes,
    billed to the tenant the disagg fleet threads through (or the
    unattributed bucket for untagged streams)."""
    if _meter is None:
        return
    _meter.wire(nbytes, tenant or UNATTRIBUTED)


def on_serve_summary() -> None:
    """Engine/fleet ``summary()`` boundary: flush per-tenant
    ``meter_ledger`` JSONL records so a finished run's stream carries
    the final ledgers (the obs_cost/report feed)."""
    if _meter is None:
        return
    _meter.emit_ledgers()


def maybe_publish(client, *, rank: int) -> bool:
    """Publish this process's ledgers through the store (the
    :func:`obs.aggregate.publish_ledgers` transport). Inert no-op when
    unarmed or nothing billed since the last publish; never raises
    into the serve loop."""
    if _meter is None:
        return False
    n = _meter._accounts
    if n == 0 or n == _meter._published:
        return False
    from pytorch_distributed_nn_tpu.obs import aggregate
    from pytorch_distributed_nn_tpu.runtime import failure

    # counted retry (store_errors_total{op="meter_publish"}): same
    # degrade-not-die contract as the heartbeat reporter — the ledger
    # stays local and the next tick republished the full state
    out = failure.store_call(
        lambda: aggregate.publish_ledgers(
            client, rank=rank, ledgers=_meter.export_ledgers()),
        op="meter_publish", deadline_s=0.5, fallback=None)
    if out is None:
        log.warning("meter ledger publish failed past deadline")
        return False
    _meter._published = n
    return True
