"""Process-wide metric registry: counters, gauges, histograms.

The reference's metrics story is ``if rank == 0: print(loss)``; ours had
a JSONL stream (utils/metrics.MetricsLogger) but no typed instruments —
every subsystem invented its own ad-hoc fields. This registry is the one
place the stack reports into:

- **instruments**: :class:`Counter` (monotone), :class:`Gauge` (set),
  :class:`Histogram` (observe into cumulative buckets), each with
  optional label names and per-label-value children;
- **backends**: Prometheus text exposition (:meth:`MetricRegistry.
  prometheus_text` — the ``text/plain; version=0.0.4`` format) and the
  existing JSONL sink (:meth:`MetricRegistry.emit_jsonl` feeds a
  ``MetricsLogger``), so one instrument serves both the scrape world and
  the benchmark-record world;
- **process-wide default**: :func:`get_registry` — module singletons are
  how library code reports without threading a handle through every
  constructor (the torch/prometheus_client idiom).

Thread-safe: producer threads (data prefetch, heartbeat) increment the
same instruments the train loop does.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Iterable, Mapping

# Default histogram buckets: latency-flavored, seconds. Wide enough for
# a 96k-token step (~13 s) and fine enough for a 1 ms MLP dispatch.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _fmt_value(v: float) -> str:
    """Prometheus float rendering: integers bare, +Inf spelled."""
    if v == math.inf:
        return "+Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _fmt_labels(names: tuple[str, ...], values: tuple[str, ...]) -> str:
    if not names:
        return ""
    body = ",".join(
        f'{k}="{_escape(v)}"' for k, v in zip(names, values)
    )
    return "{" + body + "}"


def _escape(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


class _Instrument:
    """Shared parent: name/help/label plumbing + child lookup."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labels: Iterable[str] = ()) -> None:
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self._lock = threading.Lock()
        # label-values tuple -> per-series state (subclass-defined)
        self._series: dict[tuple[str, ...], object] = {}

    def _key(self, labels: Mapping[str, object] | None) -> tuple[str, ...]:
        labels = labels or {}
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        return tuple(str(labels[k]) for k in self.label_names)


class Counter(_Instrument):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels: object) -> None:
        if value < 0:
            raise ValueError(f"{self.name}: counters only go up")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def value(self, **labels: object) -> float:
        return float(self._series.get(self._key(labels), 0.0))

    def collect(self):
        with self._lock:
            for key, v in sorted(self._series.items()):
                yield self.name, key, float(v)


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, value: float, **labels: object) -> None:
        with self._lock:
            self._series[self._key(labels)] = float(value)

    def inc(self, value: float = 1.0, **labels: object) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def value(self, **labels: object) -> float:
        return float(self._series.get(self._key(labels), 0.0))

    def collect(self):
        with self._lock:
            for key, v in sorted(self._series.items()):
                yield self.name, key, float(v)


class Histogram(_Instrument):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labels: Iterable[str] = (),
                 buckets: Iterable[float] = DEFAULT_BUCKETS) -> None:
        super().__init__(name, help, labels)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"{name}: need at least one bucket bound")
        self.buckets = bs

    def observe(self, value: float, **labels: object) -> None:
        key = self._key(labels)
        with self._lock:
            state = self._series.get(key)
            if state is None:
                state = {"counts": [0] * (len(self.buckets) + 1),
                         "sum": 0.0, "count": 0}
                self._series[key] = state
            # non-cumulative per-bucket counts; exposition cumulates
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    state["counts"][i] += 1
                    break
            else:
                state["counts"][-1] += 1  # +Inf bucket
            state["sum"] += float(value)
            state["count"] += 1

    def snapshot(self, **labels: object) -> dict:
        """{count, sum, mean} for one series (zeros when unobserved)."""
        state = self._series.get(self._key(labels))
        if state is None:
            return {"count": 0, "sum": 0.0, "mean": 0.0}
        return {"count": state["count"], "sum": state["sum"],
                "mean": state["sum"] / max(state["count"], 1)}

    def quantile(self, q: float, **labels: object) -> float:
        """Estimate the q-quantile from bucket state (the Prometheus
        ``histogram_quantile`` rule): find the cumulative bucket the
        target rank lands in and interpolate linearly inside it, from
        its lower bound (0 for the first bucket). Samples in the +Inf
        overflow bucket clamp to the last finite bound — a histogram
        can't say more than "beyond my largest bucket". Returns 0.0
        for an unobserved series, so report code can render a quiet
        column instead of branching."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"{self.name}: quantile q={q} not in [0,1]")
        with self._lock:
            state = self._series.get(self._key(labels))
            if state is None or state["count"] == 0:
                return 0.0
            counts = list(state["counts"])
            total = state["count"]
        target = q * total
        cum, lo = 0, 0.0
        for bound, n in zip(self.buckets, counts[:-1]):
            if n and cum + n >= target:
                frac = max(target - cum, 0.0) / n
                return lo + frac * (bound - lo)
            cum += n
            lo = bound
        return self.buckets[-1]  # rank falls in the +Inf tail: clamp

    def collect(self):
        """Yield exposition rows: (_bucket rows with le=), _sum, _count."""
        with self._lock:
            for key, state in sorted(self._series.items()):
                cum = 0
                for bound, n in zip(self.buckets + (math.inf,),
                                    state["counts"]):
                    cum += n
                    yield (f"{self.name}_bucket",
                           key + (_fmt_value(bound),), float(cum))
                yield f"{self.name}_sum", key, float(state["sum"])
                yield f"{self.name}_count", key, float(state["count"])


class MetricRegistry:
    """Instrument factory + exposition. ``counter``/``gauge``/
    ``histogram`` are get-or-create keyed by name, so call sites
    anywhere in the stack share one series without passing handles."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: dict[str, _Instrument] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labels: Iterable[str], **kwargs) -> _Instrument:
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, help, labels, **kwargs)
                self._instruments[name] = inst
                return inst
        if not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} already registered as {inst.kind}"
            )
        return inst

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Iterable[str] = (),
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def instruments(self) -> list[_Instrument]:
        with self._lock:
            return list(self._instruments.values())

    # -- backends --------------------------------------------------------

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4 (what a /metrics
        endpoint serves; ``promtool check metrics``-clean)."""
        out: list[str] = []
        for inst in self.instruments():
            if inst.help:
                out.append(f"# HELP {inst.name} {_escape(inst.help)}")
            out.append(f"# TYPE {inst.name} {inst.kind}")
            for name, key, value in inst.collect():
                if name.endswith("_bucket"):
                    lnames = inst.label_names + ("le",)
                else:
                    lnames = inst.label_names
                out.append(
                    f"{name}{_fmt_labels(lnames, key)} {_fmt_value(value)}"
                )
        return "\n".join(out) + ("\n" if out else "")

    def write_prometheus(self, path) -> None:
        """Textfile-collector backend (node_exporter idiom): atomic-ish
        single write of the full exposition."""
        from pathlib import Path

        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(self.prometheus_text())

    def snapshot(self) -> dict:
        """Flat JSON-able view: {metric{labels}: value}; histograms as
        {count, sum}. The cross-host aggregation payload."""
        flat: dict[str, float] = {}
        for inst in self.instruments():
            if isinstance(inst, Histogram):
                for name, key, value in inst.collect():
                    if name.endswith("_bucket"):
                        continue  # buckets stay host-local
                    lnames = inst.label_names
                    flat[name + _fmt_labels(lnames, key)] = value
            else:
                for name, key, value in inst.collect():
                    flat[name + _fmt_labels(inst.label_names, key)] = value
        return flat

    def emit_jsonl(self, logger, event: str = "metrics_snapshot") -> None:
        """One JSONL event holding the flat snapshot — the MetricsLogger
        backend (the registry absorbs it as a sink rather than
        replacing its schema)."""
        logger.emit(event, time_unix=time.time(), metrics=self.snapshot())


_default = MetricRegistry()
_default_lock = threading.Lock()


def get_registry() -> MetricRegistry:
    """The process-wide default registry."""
    return _default


def reset_registry() -> MetricRegistry:
    """Swap in a fresh default (test isolation)."""
    global _default
    with _default_lock:
        _default = MetricRegistry()
    return _default
