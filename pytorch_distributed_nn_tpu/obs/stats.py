"""Shared streaming/summary statistics for the obs stack.

Three copies of a ``_pct`` percentile helper grew independently in
``obs/forensics.py``, ``scripts/obs_report.py`` and ``scripts/serve.py``
— with three subtly different index formulas. This module is the single
implementation (nearest-rank, the forensics semantics: stable, exact on
small samples, no interpolation inventing values that never occurred),
plus the robust-location/scale helpers the watchtower's detectors run
on (median, MAD, EWMA).

Stdlib-only (like :mod:`obs.flight` / :mod:`obs.forensics`): these run
inside the doctor on a dev box and inside detector hot paths — neither
may import numpy/jax.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence


def percentile(xs: Iterable[float], q: float) -> float:
    """Nearest-rank percentile of ``xs`` at quantile ``q`` in [0, 1]
    (q=0 → min, q=1 → max). Sorts a copy; 0.0 on an empty input (the
    report-table convention: an empty column renders as zero, it does
    not throw mid-table). NaN observations are dropped before ranking:
    NaN compares false against everything, so a single contaminated
    sample would otherwise scramble the sort order and poison every
    rank — an all-NaN input therefore also renders as zero. ``median``
    and ``mad`` route through here and inherit both conventions."""
    vals = sorted(v for v in (float(x) for x in xs) if v == v)
    if not vals:
        return 0.0
    q = min(max(q, 0.0), 1.0)
    idx = min(int(q * (len(vals) - 1) + 0.5), len(vals) - 1)
    return vals[idx]


def median(xs: Iterable[float]) -> float:
    return percentile(xs, 0.5)


def mad(xs: Sequence[float], center: Optional[float] = None) -> float:
    """Median absolute deviation — the robust scale estimate the
    step-time outlier detector thresholds on (a stddev would be dragged
    by the very outliers being hunted)."""
    vals = [float(x) for x in xs]
    if not vals:
        return 0.0
    c = median(vals) if center is None else float(center)
    return median(abs(x - c) for x in vals)


class Ewma:
    """Exponentially weighted moving average (robust location for the
    online detectors). ``value`` is None until the first update."""

    def __init__(self, alpha: float = 0.1) -> None:
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = alpha
        self.value: Optional[float] = None
        self.count = 0

    def update(self, x: float) -> float:
        x = float(x)
        self.value = (x if self.value is None
                      else (1.0 - self.alpha) * self.value
                      + self.alpha * x)
        self.count += 1
        return self.value
