"""Runtime-state gauges: mesh topology + heartbeat liveness.

Surfaces what :mod:`runtime.mesh` and :mod:`runtime.failure` already
know into the metric registry, so one Prometheus scrape (or one JSONL
snapshot) answers "what shape is this job and is everyone alive"
without grepping logs:

- ``mesh_axis_size{axis=...}``, ``mesh_devices``, ``process_count``,
  ``slice_count`` — set once at trainer construction;
- worker side: ``heartbeat_age_seconds``, ``heartbeat_beats_total``,
  ``heartbeat_suppressed_total`` from the live
  :class:`runtime.failure.HeartbeatReporter` (no-ops outside the
  elastic agent);
- supervisor side: ``worker_heartbeat_age_seconds{rank=...}`` and
  ``worker_missed_beats_total{rank=...}`` from a
  :class:`runtime.failure.FailureDetector`.
"""

from __future__ import annotations

from pytorch_distributed_nn_tpu.obs.registry import (
    MetricRegistry,
    get_registry,
)


def export_mesh_gauges(mesh, registry: MetricRegistry | None = None) -> None:
    """Topology gauges from a built ``jax.sharding.Mesh``."""
    import jax

    from pytorch_distributed_nn_tpu.runtime.mesh import slice_count

    reg = registry or get_registry()
    axis = reg.gauge("mesh_axis_size", "logical mesh axis degree",
                     labels=("axis",))
    for name, size in dict(mesh.shape).items():
        axis.set(size, axis=name)
    devs = list(mesh.devices.flat)
    reg.gauge("mesh_devices", "devices in the mesh").set(len(devs))
    reg.gauge("process_count", "jax process count").set(
        jax.process_count())
    reg.gauge("slice_count", "DCN-connected TPU slices").set(
        slice_count(devs))


def update_heartbeat_gauges(registry: MetricRegistry | None = None) -> None:
    """Worker-side heartbeat state (no-op when not under the agent)."""
    from pytorch_distributed_nn_tpu.runtime import failure

    stats = failure.heartbeat_stats()
    if stats is None:
        return
    reg = registry or get_registry()
    reg.gauge("heartbeat_age_seconds",
              "seconds since this worker's last store beat").set(
        stats["age_s"])
    reg.gauge("heartbeat_beats_total",
              "beats written by this worker").set(stats["beats"])
    reg.gauge("heartbeat_suppressed_total",
              "beats withheld by the progress watchdog").set(
        stats["suppressed"])


def export_restart_gauges(*, incarnations: int, restarts: int,
                          preempt_restarts: int,
                          backoff_seconds_total: float,
                          last_exit_code: int,
                          registry: MetricRegistry | None = None) -> None:
    """Agent-side restart-policy state (launch.ElasticAgent.run): how
    many incarnations ran, how many restarts were charged to the
    budget, how many were free preemption restarts, and the backoff
    time spent — the 'lost time' side of the goodput ledger."""
    reg = registry or get_registry()
    reg.gauge("agent_incarnations_total",
              "gang incarnations launched by this agent").set(
        incarnations)
    reg.gauge("agent_restarts_total",
              "restarts charged against the budget").set(restarts)
    reg.gauge("agent_preempt_restarts_total",
              "free restarts after graceful preemption exits").set(
        preempt_restarts)
    reg.gauge("agent_backoff_seconds_total",
              "seconds spent backing off between incarnations").set(
        backoff_seconds_total)
    reg.gauge("agent_last_exit_code",
              "exit code of the last finished incarnation").set(
        last_exit_code)


def export_detector_gauges(detector,
                           registry: MetricRegistry | None = None) -> None:
    """Supervisor-side per-rank staleness gauges from a
    :class:`runtime.failure.FailureDetector`."""
    reg = registry or get_registry()
    age = reg.gauge("worker_heartbeat_age_seconds",
                    "seconds since each rank's last beat (-1 = never)",
                    labels=("rank",))
    missed = reg.gauge("worker_missed_beats_total",
                       "times each rank has been reported stale",
                       labels=("rank",))
    for rank, a in detector.last_beat_ages().items():
        age.set(-1.0 if a is None else a, rank=rank)
    for rank, n in detector.missed_counts.items():
        missed.set(n, rank=rank)
