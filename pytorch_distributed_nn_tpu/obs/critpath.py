"""Critical-path attribution over Causeway trace spans.

Input: the plain span dicts :mod:`obs.trace` emits (``{trace, span,
parent, leg, segment, host, t0, t1, ...}``), possibly joined from many
hosts (:func:`obs.aggregate.collect_spans`) or from a merged Chrome
trace (:func:`spans_from_chrome` reads back what
:func:`obs.trace.spans_to_chrome` wrote, so
:func:`obs.span.merge_chrome_traces` output stays a lossless join).

Three layers:

- :func:`assemble` — one trace's spans, leg-linked: verifies every
  leg's ``parent`` chain reaches leg 0 (the re-admitted-leg-links-to-
  original-trace invariant the failover drill asserts).
- :func:`critical_path` — partition the trace's observed extent
  ``[t0, t1]`` into attributed intervals: at every instant the
  highest-priority active duration span owns the time
  (transfer > failover > restore > prefill > decode > queued), and
  instants no span covers are ``stitch`` (scheduler glue, handoff
  rewrite, poll latency). The partition is exhaustive and disjoint BY
  CONSTRUCTION, so the per-segment seconds provably sum to the
  measured end-to-end extent — the property the tier-1 selftest pins
  to within 1% of the ticket's wall-clock latency.
- :func:`rollup` — fleet-level: every trace's dominant segment,
  bucketed by end-to-end latency SLO band, plus per-segment
  p50/p99 — "what do we fix first for the p99 band" in one table.

:func:`canonical_json` is the determinism gate's comparison unit:
structure only (ids, legs, segments, hosts, span counts), timestamps
excluded — same seed ⇒ byte-identical canonical JSON even though wall
clocks differ run to run.

Stdlib-only (no jax, no numpy).
"""

from __future__ import annotations

import json

from pytorch_distributed_nn_tpu.obs import stats

# at any instant the highest-priority active span owns the time; ties
# broken by later start (the more specific, inner phase)
PRIORITY = {"transfer": 6, "failover": 5, "restore": 4, "prefill": 3,
            "decode": 2, "queued": 1}

STITCH = "stitch"

# end-to-end latency bands the rollup groups traces into (seconds)
SLO_BUCKETS = (0.1, 0.5, 2.0)


def spans_from_chrome(events: list[dict]) -> list[dict]:
    """Recover span dicts from a (merged) Chrome trace: every event
    with ``cat == "trace"`` carries its full span in ``args``."""
    return [dict(e["args"]) for e in events
            if e.get("cat") == "trace" and "args" in e
            and "trace" in e["args"]]


def _durations(spans: list[dict]) -> list[dict]:
    """Duration spans only — marks are breadcrumbs, they never own
    critical-path time."""
    return [s for s in spans
            if s.get("segment") in PRIORITY and s["t1"] > s["t0"]]


def assemble(spans: list[dict], trace_id: str) -> dict:
    """One trace's view: spans sorted by (t0, priority), legs indexed,
    and the leg linkage verified — ``linked`` is True iff every leg
    > 0 has a ``parent`` equal to some earlier leg's root span id (the
    failover/handoff re-admission contract)."""
    mine = sorted((s for s in spans if s.get("trace") == trace_id),
                  key=lambda s: (s["t0"], -PRIORITY.get(
                      s.get("segment", ""), 0)))
    legs: dict[int, dict] = {}
    for s in mine:
        leg = legs.setdefault(int(s.get("leg", 0)), {
            "span": s.get("span", ""), "parent": s.get("parent", ""),
            "hosts": set(), "segments": {}})
        leg["hosts"].add(str(s.get("host", "")))
        seg = s.get("segment", "")
        leg["segments"][seg] = leg["segments"].get(seg, 0) + 1
    roots = {n: leg["span"] for n, leg in legs.items()}
    linked = all(
        legs[n]["parent"] in {roots[m] for m in legs if m < n}
        for n in legs if n > 0) if legs else False
    return {
        "trace": trace_id,
        "spans": mine,
        "legs": {n: {**leg, "hosts": sorted(leg["hosts"])}
                 for n, leg in sorted(legs.items())},
        "linked": linked,
    }


def critical_path(spans: list[dict]) -> dict:
    """Attribute every instant of the trace's extent to exactly one
    segment. Returns::

        {"t0": ..., "t1": ..., "total_s": t1 - t0,
         "intervals": [{"segment", "t0", "t1", "seconds"}, ...],
         "segments": {segment: seconds, ...},   # sums to total_s
         "dominant": segment}

    ``sum(segments.values()) == total_s`` holds by construction: the
    intervals are a partition of ``[t0, t1]`` (gaps are ``stitch``)."""
    durs = _durations(spans)
    if not durs:
        return {"t0": 0.0, "t1": 0.0, "total_s": 0.0,
                "intervals": [], "segments": {}, "dominant": ""}
    bounds = sorted({t for s in durs for t in (s["t0"], s["t1"])})
    t0, t1 = bounds[0], bounds[-1]
    intervals: list[dict] = []
    for lo, hi in zip(bounds, bounds[1:]):
        if hi <= lo:
            continue
        active = [s for s in durs if s["t0"] <= lo and s["t1"] >= hi]
        if active:
            win = max(active, key=lambda s: (PRIORITY[s["segment"]],
                                             s["t0"]))
            seg = win["segment"]
        else:
            seg = STITCH
        if intervals and intervals[-1]["segment"] == seg \
                and intervals[-1]["t1"] == lo:
            intervals[-1]["t1"] = hi
        else:
            intervals.append({"segment": seg, "t0": lo, "t1": hi})
    segments: dict[str, float] = {}
    for iv in intervals:
        iv["seconds"] = iv["t1"] - iv["t0"]
        segments[iv["segment"]] = (segments.get(iv["segment"], 0.0)
                                   + iv["seconds"])
    dominant = max(segments, key=lambda k: segments[k])
    return {"t0": t0, "t1": t1, "total_s": t1 - t0,
            "intervals": intervals, "segments": segments,
            "dominant": dominant}


def waterfall(spans: list[dict], trace_id: str) -> dict:
    """Render-ready single-trace view: the assembly, its critical
    path, and per-span rows with start offsets relative to the trace's
    first instant (``scripts/obs_trace.py`` draws these as bars)."""
    asm = assemble(spans, trace_id)
    cp = critical_path(asm["spans"])
    rows = [{
        "leg": int(s.get("leg", 0)),
        "segment": s.get("segment", ""),
        "host": str(s.get("host", "")),
        "start_s": round(s["t0"] - cp["t0"], 6) if cp["total_s"] else 0.0,
        "dur_s": round(s["t1"] - s["t0"], 6),
        "attrs": {k: v for k, v in s.items()
                  if k not in ("trace", "span", "parent", "leg",
                               "segment", "host", "t0", "t1")},
    } for s in _durations(asm["spans"])]
    return {"trace": trace_id, "rows": rows, "critical_path": cp,
            "legs": asm["legs"], "linked": asm["linked"]}


def rollup(spans: list[dict],
           buckets: tuple = SLO_BUCKETS) -> dict:
    """Fleet-level view across every trace present in ``spans``: per
    SLO latency band, how many traces landed there, which segment
    dominates the band's critical paths (summed seconds), and
    per-segment p50/p99 across the band's traces."""
    by_trace: dict[str, list[dict]] = {}
    for s in spans:
        by_trace.setdefault(str(s.get("trace", "")), []).append(s)
    bands: dict[str, dict] = {}
    for trace_id, mine in sorted(by_trace.items()):
        cp = critical_path(mine)
        if not cp["segments"]:
            continue
        label = next((f"<{b:g}s" for b in buckets
                      if cp["total_s"] < b), f">={buckets[-1]:g}s")
        band = bands.setdefault(label, {
            "traces": 0, "seconds": {}, "samples": {}})
        band["traces"] += 1
        for seg, sec in cp["segments"].items():
            band["seconds"][seg] = band["seconds"].get(seg, 0.0) + sec
            band["samples"].setdefault(seg, []).append(sec)
    out = {}
    order = [f"<{b:g}s" for b in buckets] + [f">={buckets[-1]:g}s"]
    for label in order:
        if label not in bands:
            continue
        band = bands[label]
        dominant = max(band["seconds"], key=lambda k: band["seconds"][k])
        out[label] = {
            "traces": band["traces"],
            "dominant": dominant,
            "segments": {
                seg: {
                    "total_s": round(band["seconds"][seg], 6),
                    "p50_s": round(stats.percentile(xs, 50.0), 6),
                    "p99_s": round(stats.percentile(xs, 99.0), 6),
                }
                for seg, xs in sorted(band["samples"].items())
            },
        }
    return out


def canonical_json(spans: list[dict]) -> str:
    """Structure-only canonical form (the ``obs_trace --selftest``
    determinism unit): ids, legs, segments, hosts and stable counts —
    every wall-clock value excluded — serialized with sorted keys, so
    the same seeded drill yields byte-identical output run to run."""
    skeleton = sorted((
        {
            "trace": s.get("trace", ""), "span": s.get("span", ""),
            "parent": s.get("parent", ""),
            "leg": int(s.get("leg", 0)),
            "segment": s.get("segment", ""),
            "mark": s.get("mark", ""),
            "host": str(s.get("host", "")),
        }
        for s in spans
    ), key=lambda d: (d["trace"], d["leg"], d["segment"], d["mark"],
                      d["host"], d["span"]))
    return json.dumps(skeleton, sort_keys=True)
