"""Xray: anomaly-triggered device profiling + per-op attribution.

The rest of the obs stack can say *that* a run is slow — goodput
decomposition (obs/goodput.py), online pages (obs/watchtower.py),
post-mortem rings (obs/flight.py + forensics). This module answers
*why*, at the op level, on three legs:

1. **Anomaly-triggered capture** — a bounded, rate-limited
   ``jax.profiler`` capture armed via ``TPUNN_XRAY=`` (chaos-style
   ``key=value:key=value`` grammar, see :class:`XrayConfig`). A capture
   fires on demand (:func:`capture_now`), every ``every`` steps, or
   when a watchtower PAGE lands (:func:`on_page`, wired from
   ``Watchtower._raise``). Each capture spans ``steps`` train/serve
   steps, then writes ``xray_summary.json`` (+ the raw perfetto trace)
   into an ``xray_<rank>_<n>_<reason>/`` directory next to the flight
   dump, and the path is named in the triggering alert's attribution
   and in ``obs_doctor --json``. ``cooldown_s`` / ``max_captures``
   bound the cost; suppressed triggers are counted, never queued.

2. **Per-op attribution** — :func:`build_attribution` merges the
   profile's slice durations (grouped per op, collectives classified
   by :data:`_COLLECTIVE_RE`) with the analytic ``utils/flops.py``
   numbers (FLOPs spread over compute rows by time share → achieved
   FLOP/s vs the chip roofline) and cross-checks collective time
   against ``CommRecorder`` wire bytes. When no device trace exists
   (``profiler=0``, or a backend without perfetto export) the table
   falls back to the flight ring's host-side dispatch windows — the
   ``collective``/``dispatch`` events with ``t0``/``t1`` stamps — so a
   capture is never empty. Rendered by ``scripts/obs_xray.py`` and
   ``scripts/obs_report.py --xray``.

3. **Compile telemetry** — when armed, a DEBUG log watch on jax's
   dispatch logger turns every ``Finished XLA compilation of
   jit(<fn>)`` line into ``xray_compiles_total`` /
   ``xray_compile_seconds`` updates, a ``xray/compile`` flight event,
   and a :func:`watchtower.on_compile` feed — the ``recompile_storm``
   detector names the function that keeps re-tracing mid-run.

The perf-regression ledger (:func:`check_ledger`) also lives here:
``bench.py --ledger`` fits a per-metric noise band (median ± k·MAD
over prior ``BENCH_r*.json`` records) and fails with a named
regression when the newest record falls out of band.

Hooks (:func:`on_step`, :func:`on_serve_round`, :func:`on_page`,
:func:`on_wire_bytes`) follow the chaos/watchtower inert-when-unset
contract — first statement is the ``_xray is None`` bail-out, AST-
checked by tests/test_quality.py — so an unarmed run pays one ``None``
check per step. Module import stays stdlib-only (jax, numpy and
ops.collectives are imported lazily inside the functions that need
them): the ledger and the capture-reading scripts must run on a dev
box with nothing but the JSON artifacts.

This module also absorbed ``utils/profiling.py`` (``xprof_trace``,
``collective_trace_seconds``, ``StepTimer``/``time_steps``,
``bus_bandwidth``), which remains as a re-export shim.
"""

from __future__ import annotations

import contextlib
import dataclasses
import glob
import gzip
import json
import logging
import os
import re
import threading
import time
from typing import Callable, Sequence

from pytorch_distributed_nn_tpu.obs import flight
from pytorch_distributed_nn_tpu.obs.registry import get_registry
from pytorch_distributed_nn_tpu.obs.stats import mad, median

log = logging.getLogger(__name__)

ENV_XRAY = "TPUNN_XRAY"

#: capture summary filename contract (scripts glob on it)
SUMMARY_NAME = "xray_summary.json"


# ---------------------------------------------------------------------------
# Spec grammar (chaos/watchtower-style): TPUNN_XRAY="steps=5:cooldown_s=30"
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class XrayConfig:
    """Capture policy. Every field is a spec key."""

    every: int = 0          # capture every N steps (0 = trigger-only)
    steps: int = 3          # step window one capture spans
    max_captures: int = 3   # lifetime cap per process
    cooldown_s: float = 60.0  # min seconds between capture starts
    on_page: int = 1        # 1 = a watchtower PAGE triggers a capture
    profiler: int = 1       # 1 = real jax.profiler trace; 0 = ring-only
    perfetto: int = 1       # write perfetto_trace.json.gz (parseable)
    dir: str = ""           # capture root override (default: flight dir)


_FIELD_TYPES = {f.name: f.type for f in dataclasses.fields(XrayConfig)}


def parse_spec(spec: str) -> XrayConfig:
    """``""``/``"1"``/``"on"``/``"true"`` → defaults; otherwise
    ``key=value`` pairs joined by ``:``. Unknown keys and malformed
    values raise — an armed profiler must never silently no-op."""
    cfg = XrayConfig()
    spec = spec.strip()
    if spec.lower() in ("", "1", "on", "true"):
        return cfg
    for part in spec.split(":"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"xray spec {spec!r}: expected key=value, got {part!r}")
        key, value = part.split("=", 1)
        key = key.strip()
        kind = _FIELD_TYPES.get(key)
        if kind is None:
            raise ValueError(
                f"xray spec {spec!r}: unknown key {key!r} "
                f"(known: {sorted(_FIELD_TYPES)})")
        try:
            if kind in (str, "str"):
                cast = value.strip()
            elif kind in (int, "int"):
                cast = int(value)
            else:
                cast = float(value)
        except ValueError:
            raise ValueError(
                f"xray spec {spec!r}: bad value {value!r} for {key!r}")
        setattr(cfg, key, cast)
    _validate(cfg)
    return cfg


def _validate(cfg: XrayConfig) -> None:
    if cfg.steps < 1:
        raise ValueError(f"xray: steps must be >= 1, got {cfg.steps}")
    if cfg.max_captures < 1:
        raise ValueError(
            f"xray: max_captures must be >= 1, got {cfg.max_captures}")
    if cfg.cooldown_s < 0:
        raise ValueError(
            f"xray: cooldown_s must be >= 0, got {cfg.cooldown_s}")
    if cfg.every < 0:
        raise ValueError(f"xray: every must be >= 0, got {cfg.every}")


# ---------------------------------------------------------------------------
# Profiling primitives (absorbed from utils/profiling.py)
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def xprof_trace(log_dir: str, *, perfetto: bool = False):
    """Capture an XProf/TensorBoard trace of the enclosed steps.
    ``perfetto=True`` additionally writes ``perfetto_trace.json.gz``
    (Chrome trace-event JSON), which :func:`collective_trace_seconds`
    parses — XProf's xplane protos need the TensorBoard profile plugin
    that this container doesn't ship."""
    import jax

    jax.profiler.start_trace(log_dir, create_perfetto_trace=perfetto)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# Collective-op slice names across backends: TPU emits fusion/op names
# like 'all-reduce.3' / 'all-reduce-start'; XLA CPU emits the HLO name
# ('psum_invariant.7', 'collective-permute', ...). Python-level slices
# ('$file.py:123 fn') and paired 'end: <op>' markers are excluded.
_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|all-to-all|reduce-scatter|"
    r"collective-permute|collective-broadcast|psum|ppermute|"
    r"allreduce|allgather)", re.IGNORECASE,
)


def _newest_perfetto(log_dir: str) -> str | None:
    """Newest perfetto trace under a profiler log dir — by mtime, not
    by name: profiler run dirs are timestamp strings whose lexicographic
    order need not match creation order (clock changes, host renames,
    re-used dirs)."""
    paths = glob.glob(
        os.path.join(str(log_dir), "**", "perfetto_trace.json.gz"),
        recursive=True,
    )
    if not paths:
        return None
    return max(paths, key=os.path.getmtime)


@dataclasses.dataclass
class CollectiveTrace:
    """Profile-derived collective time (see collective_trace_seconds)."""

    total_s: float  # summed slice duration across ALL device tracks
    per_device_s: float  # total_s / device participant count
    n_events: int
    names: dict[str, float]  # per-op-name seconds (diagnostics)


def collective_trace_seconds(log_dir: str,
                             world: int) -> CollectiveTrace | None:
    """Parse the newest perfetto trace under ``log_dir`` and sum the
    durations of collective-op slices (BASELINE.json bus-bw metric,
    VERDICT r2 Missing #3: bus bandwidth derived *from profile*, not
    from wire-byte bookkeeping alone).

    Each participating device contributes its own slice per executed
    collective, so ``per_device_s = total / world`` is the average time
    one device spent inside collectives. Async pairs (TPU
    'all-reduce-start'/'-done') both count — start covers the transfer
    window, done the wait — so the figure is an upper bound on wire
    occupancy; the cross-check against analytic wire bytes in
    ``bench.py --metric bus_bw`` reports both. Returns None when no
    trace file or no collective slices are found (e.g. world == 1 —
    XLA elides the collectives entirely)."""
    path = _newest_perfetto(log_dir)
    if path is None:
        return None
    with gzip.open(path) as f:
        tr = json.load(f)
    events = tr["traceEvents"] if isinstance(tr, dict) else tr
    rx = _COLLECTIVE_RE
    total_us = 0.0
    names: dict[str, float] = {}
    n = 0
    for e in events:
        name = e.get("name", "")
        if (e.get("ph") != "X" or name.startswith("$")
                or name.startswith("end: ") or not rx.search(name)):
            continue
        dur = float(e.get("dur", 0.0))
        total_us += dur
        names[name] = names.get(name, 0.0) + dur / 1e6
        n += 1
    if n == 0:
        return None
    return CollectiveTrace(
        total_s=total_us / 1e6,
        per_device_s=total_us / 1e6 / max(world, 1),
        n_events=n,
        names=names,
    )


class StepTimer:
    """Wall-clock per-step timer with device fencing."""

    def __init__(self) -> None:
        self.times: list[float] = []
        self._t0: float | None = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, *fence) -> float:
        """Record one step; ``fence`` arrays are blocked on first."""
        if fence:
            import jax

            jax.block_until_ready(fence)
        dt = time.perf_counter() - self._t0
        self.times.append(dt)
        return dt

    def summary(self) -> dict[str, float]:
        if not self.times:
            # an unstarted/empty timer must summarize, not crash
            # (np.percentile([]) raises): zeros, steps=0
            return {"steps": 0, "mean_s": 0.0, "p50_s": 0.0,
                    "p95_s": 0.0, "total_s": 0.0}
        import numpy as np

        ts = np.array(self.times)
        return {
            "steps": len(ts),
            "mean_s": float(ts.mean()),
            "p50_s": float(np.percentile(ts, 50)),
            "p95_s": float(np.percentile(ts, 95)),
            "total_s": float(ts.sum()),
        }


def time_steps(step_fn: Callable, args_fn: Callable[[int], tuple], *,
               iters: int, warmup: int = 3,
               carry_state: bool = True) -> StepTimer:
    """Time ``iters`` executions of ``step_fn``. ``args_fn(i)`` yields the
    per-step ``(state, *batch)`` args; when ``carry_state`` the returned
    state threads into the next call (the real training pattern)."""
    import jax

    state, *batch = args_fn(0)
    for i in range(warmup):
        out = step_fn(state, *batch)
        state = out[0] if carry_state else state
        _, *batch = args_fn(i + 1)
    jax.block_until_ready(state)
    timer = StepTimer()
    for i in range(iters):
        timer.start()
        out = step_fn(state, *batch)
        new_state = out[0] if carry_state else state
        timer.stop(new_state)
        state = new_state
        _, *batch = args_fn(warmup + i + 1)
    return timer


@dataclasses.dataclass
class BusBandwidth:
    wire_gbps: float  # GB/s of link traffic per device
    wire_bytes_per_step: float
    step_s: float
    records: int


def bus_bandwidth(records: Sequence, step_s: float) -> BusBandwidth:
    """Ring-accounted wire bytes per device / measured step time — the
    comparable of NCCL's busbw (nccl-tests definition)."""
    from pytorch_distributed_nn_tpu.ops import collectives as cc

    wire = cc.wire_bytes(records)
    return BusBandwidth(
        wire_gbps=wire / step_s / 1e9 if step_s > 0 else 0.0,
        wire_bytes_per_step=wire,
        step_s=step_s,
        records=len(records),
    )


# ---------------------------------------------------------------------------
# Per-op attribution
# ---------------------------------------------------------------------------

def _trace_op_rows(log_dir: str) -> list[dict]:
    """Per-op rows from the newest perfetto trace: one row per slice
    name, collectives classified by :data:`_COLLECTIVE_RE`."""
    path = _newest_perfetto(log_dir)
    if path is None:
        return []
    try:
        with gzip.open(path) as f:
            tr = json.load(f)
    except (OSError, ValueError):
        return []
    events = tr["traceEvents"] if isinstance(tr, dict) else tr
    agg: dict[str, dict] = {}
    for e in events:
        name = e.get("name", "")
        if (e.get("ph") != "X" or name.startswith("$")
                or name.startswith("end: ")):
            continue
        cat = ("collective" if _COLLECTIVE_RE.search(name)
               else "compute")
        row = agg.setdefault(name, {"op": name, "category": cat,
                                    "calls": 0, "time_s": 0.0,
                                    "nbytes": 0})
        row["calls"] += 1
        row["time_s"] += float(e.get("dur", 0.0)) / 1e6
    return list(agg.values())


def _ring_op_rows(events: list[dict]) -> list[dict]:
    """Per-op rows from flight-ring events — the host-side fallback
    when no device trace exists. ``collective`` dispatch windows and
    ``dispatch`` (fused step program) events carry ``t0``/``t1``
    stamps; trace-time records (``t1 == t0``, duration 0) still count
    calls and bytes."""
    agg: dict[tuple, dict] = {}
    for e in events:
        kind = e.get("kind")
        if kind not in ("collective", "dispatch"):
            continue
        op = str(e.get("op", "")) or kind
        cat = "collective" if kind == "collective" else "compute"
        t0, t1 = e.get("t0"), e.get("t1")
        dur = (t1 - t0) if (t0 is not None and t1 is not None) else 0.0
        row = agg.setdefault((cat, op), {"op": op, "category": cat,
                                         "calls": 0, "time_s": 0.0,
                                         "nbytes": 0})
        row["calls"] += 1
        row["time_s"] += max(float(dur), 0.0)
        row["nbytes"] += int(e.get("nbytes", 0) or 0)
    return list(agg.values())


def build_attribution(*, trace_dir: str | None = None,
                      events: list[dict] | None = None,
                      wire_bytes_per_step: float | None = None,
                      flops_per_step: float | None = None,
                      steps: int = 1,
                      peak_flops: float | None = None,
                      top: int = 16) -> dict:
    """The per-op table: time share per op, analytic FLOPs spread over
    compute rows by time share (→ achieved FLOP/s, roofline fraction
    when a chip peak is known), and the collective block cross-checked
    against ``CommRecorder`` wire bytes. Prefers real trace slices;
    falls back to flight-ring dispatch windows so a ``profiler=0``
    capture still attributes."""
    rows: list[dict] = []
    source = "none"
    if trace_dir:
        rows = _trace_op_rows(trace_dir)
        if rows:
            source = "trace"
    if not rows and events:
        rows = _ring_op_rows(events)
        if rows:
            source = "flight_ring"
    total = sum(r["time_s"] for r in rows)
    for r in rows:
        r["share"] = r["time_s"] / total if total > 0 else 0.0
    rows.sort(key=lambda r: (-r["time_s"], r["op"]))

    compute_t = sum(r["time_s"] for r in rows
                    if r["category"] == "compute")
    if flops_per_step and compute_t > 0:
        # no per-op FLOP counts without an HLO cost analysis pass, so
        # the analytic step total is attributed by time share — exact
        # in aggregate, approximate per row (stated in the docs)
        total_flops = float(flops_per_step) * max(int(steps), 1)
        for r in rows:
            if r["category"] != "compute" or r["time_s"] <= 0:
                continue
            r["flops"] = total_flops * (r["time_s"] / compute_t)
            r["achieved_flops_per_s"] = r["flops"] / r["time_s"]
            if peak_flops:
                r["roofline_frac"] = (r["achieved_flops_per_s"]
                                      / float(peak_flops))

    coll_t = sum(r["time_s"] for r in rows
                 if r["category"] == "collective")
    coll_b = sum(r["nbytes"] for r in rows
                 if r["category"] == "collective")
    comm: dict = {
        "collective_s": coll_t,
        "collective_share": coll_t / total if total > 0 else 0.0,
        "ring_nbytes": coll_b,
    }
    if wire_bytes_per_step is not None:
        expected = float(wire_bytes_per_step) * max(int(steps), 1)
        comm["wire_bytes_per_step"] = float(wire_bytes_per_step)
        comm["expected_wire_bytes"] = expected
        if coll_t > 0:
            comm["implied_gbps"] = expected / coll_t / 1e9
        if coll_b and expected:
            comm["ring_vs_recorder"] = coll_b / expected

    rows = rows[:max(int(top), 1)]
    return {
        "source": source,
        "total_s": total,
        "rows": rows,
        "comm": comm,
        "top_op": rows[0]["op"] if rows else "",
        "top_category": rows[0]["category"] if rows else "",
        "top_share": rows[0]["share"] if rows else 0.0,
    }


def find_captures(directory) -> list[str]:
    """All capture summaries under a run dir (the doctor/report glob):
    ``xray_*/xray_summary.json`` plus a bare summary, oldest first."""
    root = str(directory)
    paths = set(glob.glob(os.path.join(root, "xray_*", SUMMARY_NAME)))
    direct = os.path.join(root, SUMMARY_NAME)
    if os.path.exists(direct):
        paths.add(direct)
    return sorted(paths, key=os.path.getmtime)


def load_capture(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def render_op_table(att: dict, *, top: int = 12) -> str:
    """Fixed-width per-op table (scripts/obs_xray.py, obs_report
    --xray)."""
    lines = [
        f"source: {att.get('source', '?')}   total "
        f"{att.get('total_s', 0.0):.4f}s   collective share "
        f"{att.get('comm', {}).get('collective_share', 0.0):.1%}",
        f"{'op':<44} {'cat':<10} {'calls':>6} {'time_s':>9} "
        f"{'share':>7} {'roofline':>8}",
    ]
    for r in att.get("rows", [])[:top]:
        roof = r.get("roofline_frac")
        lines.append(
            f"{r['op'][:44]:<44} {r['category']:<10} {r['calls']:>6} "
            f"{r['time_s']:>9.4f} {r['share']:>7.1%} "
            f"{(f'{roof:.1%}' if roof is not None else '-'):>8}")
    comm = att.get("comm", {})
    if comm.get("implied_gbps") is not None:
        lines.append(
            f"comm cross-check: {comm.get('expected_wire_bytes', 0):.0f}"
            f" recorder wire bytes over {comm['collective_s']:.4f}s "
            f"collective time -> {comm['implied_gbps']:.2f} GB/s")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Compile telemetry: jax dispatch-log watch
# ---------------------------------------------------------------------------

# jax logs "Finished XLA compilation of jit(<fn>) in <secs> sec" (and
# "Finished tracing + transforming <fn> for pjit in ...") at DEBUG on
# its dispatch logger; the duration-only jax.monitoring events carry no
# function name, so the log line is the only place both live together.
_COMPILE_LOGGER = "jax._src.dispatch"
_COMPILE_MSG_RE = re.compile(
    r"Finished XLA compilation of (.+?) in ([0-9.eE+-]+) sec")


class _CompileLogHandler(logging.Handler):
    """Tap + relay. Installing the tap forces the dispatch logger down to
    DEBUG and cuts propagation (else arming xray would spray every jax
    compile line onto the app's console); records at or above the
    logger's previous effective level are relayed to root so warnings
    still surface exactly as before."""

    def __init__(self, engine: "XrayEngine",
                 relay_level: int = logging.WARNING) -> None:
        super().__init__(level=logging.DEBUG)
        self._engine = engine
        self._relay_level = relay_level

    def emit(self, record: logging.LogRecord) -> None:  # noqa: A003
        try:
            m = _COMPILE_MSG_RE.search(record.getMessage())
            if m:
                self._engine._on_compile(m.group(1), float(m.group(2)))
            if record.levelno >= self._relay_level:
                root = logging.getLogger()
                if root.isEnabledFor(record.levelno):
                    root.handle(record)
        except Exception:  # a telemetry tap must never break dispatch
            pass


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

class XrayEngine:
    """Capture policy + compile watch + attribution writer. All entry
    points take an explicit ``t`` so the rate limiter is testable with
    injected clocks; module hooks stamp ``time.time()``."""

    def __init__(self, config: XrayConfig | None = None, *,
                 rank: int | None = None,
                 base_dir=None) -> None:
        self.cfg = config or XrayConfig()
        _validate(self.cfg)
        self.rank = flight.default_rank() if rank is None else int(rank)
        self._base_dir = str(base_dir) if base_dir else ""
        self.captures: list[dict] = []
        self.suppressed: dict[str, int] = {}
        # cost context fed by the trainer / bench (cross-checks)
        self.wire_bytes_per_step: float | None = None
        self.flops_per_step: float | None = None
        self.peak_flops: float | None = None
        # compile telemetry
        self.compile_counts: dict[str, int] = {}
        self.compile_seconds_total = 0.0
        self._compile_handler: _CompileLogHandler | None = None
        self._compile_prev_level: int | None = None
        self._compile_prev_propagate: bool = True
        self._active: dict | None = None
        self._last_capture_t: float | None = None
        self._n_started = 0
        self._lock = threading.Lock()
        reg = get_registry()
        self._c_captures = reg.counter(
            "xray_captures_total", "profiler captures started",
            labels=("trigger",))
        self._c_suppressed = reg.counter(
            "xray_suppressed_total",
            "capture triggers dropped by the rate limiter",
            labels=("reason",))
        self._c_compiles = reg.counter(
            "xray_compiles_total", "XLA compilations observed")
        self._g_compile_s = reg.gauge(
            "xray_compile_seconds",
            "cumulative seconds spent in XLA compilation")

    # -- capture lifecycle -----------------------------------------------

    def step(self, step: int, t: float | None = None) -> None:
        """One train step / serve round: advances an active capture
        window (finishing it when it has spanned ``cfg.steps``) or
        starts an interval capture on ``cfg.every`` boundaries."""
        t = time.time() if t is None else t
        if self._active is not None:
            self._active["remaining"] -= 1
            if self._active["remaining"] <= 0:
                self._finish(t)
        elif (self.cfg.every > 0 and step > 0
                and step % self.cfg.every == 0):
            self.request_capture("interval", step=step, t=t)

    def page(self, kind: str, *, step: int = -1,
             t: float | None = None) -> str | None:
        """A watchtower PAGE landed; capture unless ``on_page=0``."""
        if not self.cfg.on_page:
            return None
        return self.request_capture(f"page:{kind}", step=step, t=t)

    def request_capture(self, reason: str, *, step: int = -1,
                        t: float | None = None) -> str | None:
        """The one choke point every trigger goes through: enforces the
        busy / lifetime / cooldown bounds, counts what it drops, and
        returns the capture directory (or None when suppressed)."""
        t = time.time() if t is None else t
        with self._lock:
            if self._active is not None:
                why = "busy"
            elif self._n_started >= self.cfg.max_captures:
                why = "max_captures"
            elif (self._last_capture_t is not None
                    and t - self._last_capture_t < self.cfg.cooldown_s):
                why = "cooldown"
            else:
                why = None
                self._last_capture_t = t
                self._n_started += 1
        if why is not None:
            self.suppressed[why] = self.suppressed.get(why, 0) + 1
            self._c_suppressed.inc(reason=why)
            return None
        return self._capture(reason, step, t, self._next_dir(reason))

    def _capture(self, reason: str, step: int, t: float,
                 cap_dir: str) -> str:
        """Start one capture window. The flight event is FIRST (AST-
        linted): if the profiler itself wedges the process, the ring
        that reaches disk already says a capture was starting."""
        flight.record("xray", "capture", step=step,
                      note=f"{reason} -> {cap_dir}")
        self._c_captures.inc(trigger=reason.split(":", 1)[0])
        profiling = False
        if self.cfg.profiler:
            try:
                import jax

                jax.profiler.start_trace(
                    cap_dir, create_perfetto_trace=bool(self.cfg.perfetto))
                profiling = True
            except Exception as e:
                log.warning(
                    "xray: profiler start failed (%s); ring-only capture",
                    e)
        self._active = {
            "reason": reason, "dir": cap_dir, "step": step,
            "t_start": t, "remaining": max(self.cfg.steps, 1),
            "profiling": profiling,
        }
        return cap_dir

    def _next_dir(self, reason: str) -> str:
        base = (self._base_dir or self.cfg.dir
                or flight.resolve_dump_dir())
        slug = re.sub(r"[^A-Za-z0-9_.=-]+", "-", reason)
        d = os.path.join(
            base, f"xray_{self.rank}_{self._n_started - 1:02d}_{slug}")
        try:
            os.makedirs(d, exist_ok=True)
        except OSError as e:
            log.warning("xray: cannot create %s (%s)", d, e)
        return d

    def _finish(self, t: float) -> dict | None:
        act, self._active = self._active, None
        if act is None:
            return None
        if act["profiling"]:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                log.warning("xray: profiler stop failed: %s", e)
        events = [e for e in flight.get_recorder().snapshot()
                  if e.get("t0") is not None
                  and e["t0"] >= act["t_start"] - 1e-3]
        peak = self.peak_flops
        if peak is None:
            try:
                from pytorch_distributed_nn_tpu.utils.flops import (
                    peak_flops_per_chip,
                )

                peak = peak_flops_per_chip()  # None off-TPU
            except Exception:
                peak = None
        att = build_attribution(
            trace_dir=act["dir"] if act["profiling"] else None,
            events=events,
            wire_bytes_per_step=self.wire_bytes_per_step,
            flops_per_step=self.flops_per_step,
            steps=max(self.cfg.steps, 1),
            peak_flops=peak,
        )
        summary = {
            "reason": act["reason"], "rank": self.rank,
            "trigger_step": act["step"], "t_start": act["t_start"],
            "t_end": t, "steps": max(self.cfg.steps, 1),
            "dir": act["dir"], "profiler": bool(act["profiling"]),
            "compiles": dict(self.compile_counts),
            "compile_seconds": self.compile_seconds_total,
            "attribution": att,
        }
        path = os.path.join(act["dir"], SUMMARY_NAME)
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(summary, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError as e:
            log.warning("xray: summary write failed: %s", e)
        flight.record(
            "xray", "capture_done", step=act["step"],
            note=f"{act['reason']} top={att['top_op'] or '?'} "
                 f"-> {act['dir']}")
        self.captures.append(summary)
        return summary

    # -- compile telemetry -----------------------------------------------

    def _install_compile_watch(self) -> None:
        """DEBUG log watch on jax's dispatch logger (idempotent)."""
        if self._compile_handler is not None:
            return
        lg = logging.getLogger(_COMPILE_LOGGER)
        self._compile_prev_level = lg.level
        self._compile_prev_propagate = lg.propagate
        self._compile_handler = _CompileLogHandler(
            self, relay_level=lg.getEffectiveLevel())
        lg.addHandler(self._compile_handler)
        lg.propagate = False
        if lg.getEffectiveLevel() > logging.DEBUG:
            lg.setLevel(logging.DEBUG)

    def _uninstall_compile_watch(self) -> None:
        if self._compile_handler is None:
            return
        lg = logging.getLogger(_COMPILE_LOGGER)
        lg.removeHandler(self._compile_handler)
        if self._compile_prev_level is not None:
            lg.setLevel(self._compile_prev_level)
        lg.propagate = self._compile_prev_propagate
        self._compile_handler = None

    def _on_compile(self, name: str, seconds: float) -> None:
        """One observed XLA compilation (from the log watch, or fed
        directly in tests): counters, a flight breadcrumb, and the
        watchtower recompile_storm feed."""
        if name.startswith("jit(") and name.endswith(")"):
            name = name[4:-1]
        with self._lock:
            self.compile_counts[name] = (
                self.compile_counts.get(name, 0) + 1)
            self.compile_seconds_total += float(seconds)
            total = self.compile_seconds_total
        self._c_compiles.inc()
        self._g_compile_s.set(total)
        flight.record("xray", "compile", note=f"{name} {seconds:.3f}s")
        # lazy on purpose: watchtower imports xray at module level, so
        # the reverse edge must stay out of import time
        from pytorch_distributed_nn_tpu.obs import watchtower

        watchtower.on_compile(name, seconds)

    # -- teardown ---------------------------------------------------------

    def close(self, t: float | None = None) -> None:
        """Disarm: finish any open capture and restore jax's logger."""
        if self._active is not None:
            self._finish(time.time() if t is None else t)
        self._uninstall_compile_watch()

    def summary(self) -> dict:
        return {
            "captures": len(self.captures),
            "suppressed": dict(self.suppressed),
            "compiles": dict(self.compile_counts),
            "compile_seconds": self.compile_seconds_total,
            "paths": [c["dir"] for c in self.captures],
        }


# ---------------------------------------------------------------------------
# Perf-regression ledger (bench.py --ledger)
# ---------------------------------------------------------------------------

# substrings that mark a lower-is-better metric; everything else
# (throughput, MFU, bandwidth, accuracy) regresses downward
_LOWER_IS_BETTER = ("nll", "latency", "ttft", "_ms", " ms", "seconds",
                    "cost")


def metric_direction(name: str) -> str:
    low = name.lower()
    return ("lower" if any(s in low for s in _LOWER_IS_BETTER)
            else "higher")


def _parse_tail_metrics(tail) -> list[dict]:
    """Benchmark records embedded in a record's captured-stdout
    ``tail``. The driver parses ONE record per round into ``parsed``,
    but a round that benches several series in one invocation (e.g.
    ``--fleet`` emitting the thread-fleet AND the ``--fleet-procs`` /
    ``--disagg`` series) prints one JSON line per series; this
    recovers the rest so every emitted series joins the tracked
    trajectory. Accepts both shapes MetricsLogger produces — the
    event-wrapped ``{"event": "benchmark", ...}`` line and the bare
    ``{"metric", "value", "unit", ...}`` record — and tolerates a
    missing/garbled tail (older and synthetic records have none)."""
    if isinstance(tail, str):
        lines = tail.splitlines()
    elif isinstance(tail, (list, tuple)):
        lines = [str(x) for x in tail]
    else:
        return []
    out = []
    for ln in lines:
        ln = ln.strip()
        if not (ln.startswith("{") and '"metric"' in ln):
            continue
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if not isinstance(d, dict) \
                or d.get("event") not in (None, "benchmark") \
                or not isinstance(d.get("metric"), str) \
                or not isinstance(d.get("value"), (int, float)):
            continue
        out.append({k: v for k, v in d.items()
                    if k not in ("event", "time", "process")})
    return out


def load_bench_records(directory=".",
                       pattern: str = "BENCH_r*.json") -> list[dict]:
    """The BENCH_r*.json trajectory, ordered by round number ``n``.
    Unreadable files are skipped (a torn write must not kill the
    gate); records with ``parsed: null`` (failed runs) are kept so the
    checker can report how many it ignored. Extra benchmark lines in
    each record's stdout tail land in ``_tail_metrics`` so multi-series
    rounds track every series they emitted."""
    recs = []
    for p in sorted(glob.glob(os.path.join(str(directory), pattern))):
        try:
            with open(p) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        rec.setdefault("_path", p)
        rec["_tail_metrics"] = _parse_tail_metrics(rec.get("tail"))
        recs.append(rec)
    recs.sort(key=lambda r: (int(r.get("n", 1 << 30)),
                             str(r.get("_path", ""))))
    return recs


def fit_noise_band(values: Sequence[float], *, mad_k: float = 4.0,
                   rel_floor: float = 0.05) -> dict:
    """median ± max(k·MAD, rel_floor·|median|). The MAD term tracks the
    observed run-to-run noise; the relative floor keeps a freakishly
    quiet history (MAD ≈ 0 on 2-3 records) from flagging 1% jitter."""
    vals = [float(v) for v in values]
    med = median(vals)
    spread = mad(vals, center=med)
    half = max(mad_k * spread, rel_floor * abs(med))
    return {"median": med, "mad": spread,
            "lo": med - half, "hi": med + half}


def check_ledger(records: list[dict], *, mad_k: float = 4.0,
                 rel_floor: float = 0.05,
                 min_history: int = 2) -> dict:
    """The regression gate: per metric, fit the noise band over all
    PRIOR parsed records and test the newest one against it (direction-
    aware — throughput regresses below band, NLL/latency above). Named
    verdicts; ``ok`` is False only on a confirmed regression."""
    series: dict[str, list[tuple[int, float, str]]] = {}
    skipped = 0
    for rec in records:
        entries = []
        parsed = rec.get("parsed")
        if (isinstance(parsed, dict)
                and isinstance(parsed.get("value"), (int, float))):
            entries.append(parsed)
        # multi-series rounds: the driver's single `parsed` slot only
        # holds one record; the rest ride in from the stdout tail
        # (load_bench_records), deduped on the series name
        seen = {str(e.get("metric", "unnamed")) for e in entries}
        for extra in rec.get("_tail_metrics") or ():
            if str(extra.get("metric", "unnamed")) not in seen:
                entries.append(extra)
                seen.add(str(extra.get("metric", "unnamed")))
        if not entries:
            skipped += 1
            continue
        for parsed in entries:
            metric = str(parsed.get("metric", "unnamed"))
            series.setdefault(metric, []).append(
                (int(rec.get("n", -1)), float(parsed["value"]),
                 str(rec.get("_path", ""))))
    metrics = []
    regressions = []
    for metric in sorted(series):
        pts = series[metric]
        n, value, path = pts[-1]
        prior = [v for _, v, _ in pts[:-1]]
        entry: dict = {"metric": metric, "n": n, "value": value,
                       "direction": metric_direction(metric),
                       "history": len(prior), "path": path}
        if len(prior) < min_history:
            entry["status"] = "insufficient_history"
            metrics.append(entry)
            continue
        band = fit_noise_band(prior, mad_k=mad_k, rel_floor=rel_floor)
        entry.update(band)
        bad = (value > band["hi"] if entry["direction"] == "lower"
               else value < band["lo"])
        entry["status"] = "regression" if bad else "ok"
        if bad:
            bound = band["hi" if entry["direction"] == "lower" else "lo"]
            regressions.append(
                f"{metric}: r{n} = {value:g} is outside the noise band "
                f"(bound {bound:g}; median {band['median']:g}, "
                f"MAD {band['mad']:g}, k={mad_k:g}, "
                f"floor {rel_floor:.0%})")
        metrics.append(entry)
    return {"ok": not regressions, "metrics": metrics,
            "regressions": regressions, "skipped_records": skipped}


# ---------------------------------------------------------------------------
# Process-wide singleton + inert hooks (the chaos/watchtower contract)
# ---------------------------------------------------------------------------

_xray: XrayEngine | None = None


def maybe_init(spec: str | None = None, *, rank: int | None = None,
               base_dir=None) -> XrayEngine | None:
    """Arm from ``TPUNN_XRAY`` (or an explicit spec). Idempotent;
    returns None when unset / "0" — the inert path."""
    global _xray
    if _xray is not None:
        return _xray
    if spec is None:
        spec = os.environ.get(ENV_XRAY, "")
    if not spec or spec.strip() == "0":
        return None
    cfg = parse_spec(spec)
    _xray = XrayEngine(cfg, rank=rank, base_dir=base_dir)
    _xray._install_compile_watch()
    log.info("xray armed: %s", cfg)
    return _xray


def enabled() -> bool:
    return _xray is not None


def engine() -> XrayEngine | None:
    return _xray


def reset() -> None:
    """Disarm and forget (test isolation)."""
    global _xray
    if _xray is not None:
        _xray._uninstall_compile_watch()
    _xray = None


def capture_now(reason: str = "manual", step: int = -1) -> str | None:
    """On-demand capture (still rate-limited); None when unarmed or
    suppressed."""
    if _xray is None:
        return None
    return _xray.request_capture(reason, step=step)


# hooks: first statement is the bail-out (AST-linted inert fast path)

def on_step(step: int) -> None:
    """Trainer step boundary."""
    if _xray is None:
        return
    _xray.step(int(step), t=time.time())


def on_serve_round(round_idx: int) -> None:
    """Serving decode round (the serving-side step clock)."""
    if _xray is None:
        return
    _xray.step(int(round_idx), t=time.time())


def on_page(kind: str, step: int = -1):
    """A watchtower PAGE landed; returns the capture dir (or None)."""
    if _xray is None:
        return
    return _xray.page(str(kind), step=int(step), t=time.time())


def on_wire_bytes(nbytes: float) -> None:
    """Analytic wire bytes per step (CommRecorder) for the comm
    cross-check."""
    if _xray is None:
        return
    _xray.wire_bytes_per_step = float(nbytes)


def on_flops(flops_per_step: float) -> None:
    """Analytic model FLOPs per step per chip (utils/flops.py cost
    model, fed by the trainer) — what turns time shares into achieved
    FLOP/s and roofline fractions in the attribution table."""
    if _xray is None:
        return
    _xray.flops_per_step = float(flops_per_step)
