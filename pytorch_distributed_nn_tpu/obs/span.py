"""Span tracing: Chrome trace-event JSON per host.

``with obs.span("data/next_batch"): ...`` marks a host-side phase. When
tracing is disabled (the default) a span costs one module-global read
and yields a shared null context — no allocation, no clock read — so
instrumentation can stay in the hot loop permanently.

Enabled (:func:`enable_tracing`), spans record complete events
(``ph: "X"``, microsecond ``ts``/``dur``) into an in-memory buffer that
:func:`write_trace` serializes as Chrome trace-event JSON — the same
format ``jax.profiler``'s ``perfetto_trace.json.gz`` uses, so
:func:`merge_chrome_traces` can splice host spans and device slices
into one timeline (chrome://tracing / Perfetto both open it).

Thread-safe: producer threads (data prefetch) trace under the same
recorder; ``tid`` keeps their tracks apart.
"""

from __future__ import annotations

import gzip
import json
import os
import threading
import time
from pathlib import Path


class _NullSpan:
    """Reusable disabled-tracing context (one instance, no state)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class TraceRecorder:
    """In-memory trace-event buffer for one process."""

    def __init__(self, *, process_index: int = 0,
                 process_name: str | None = None) -> None:
        self.process_index = process_index
        self.process_name = process_name or f"host{process_index}"
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._t0 = time.perf_counter()
        # wall-clock anchor so merged traces share an epoch
        self.epoch_unix = time.time()

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def add_event(self, name: str, ts_us: float, dur_us: float,
                  cat: str = "app", args: dict | None = None) -> None:
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": ts_us, "dur": dur_us,
              "pid": self.process_index,
              "tid": threading.get_ident() & 0xFFFF}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def instant(self, name: str, cat: str = "app",
                args: dict | None = None) -> None:
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": self._now_us(), "pid": self.process_index,
              "tid": threading.get_ident() & 0xFFFF}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def trace_json(self) -> dict:
        meta = [{"name": "process_name", "ph": "M",
                 "pid": self.process_index,
                 "args": {"name": self.process_name}}]
        return {"traceEvents": meta + self.events(),
                "displayTimeUnit": "ms",
                "otherData": {"epoch_unix": self.epoch_unix}}


class _Span:
    __slots__ = ("_rec", "_name", "_cat", "_args", "_t0")

    def __init__(self, rec: TraceRecorder, name: str, cat: str,
                 args: dict | None) -> None:
        self._rec = rec
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        self._t0 = self._rec._now_us()
        return self

    def __exit__(self, *exc):
        t1 = self._rec._now_us()
        self._rec.add_event(self._name, self._t0, t1 - self._t0,
                            self._cat, self._args)
        return False


_recorder: TraceRecorder | None = None


def span(name: str, cat: str = "app", **args):
    """Context manager marking a host-side phase. Free when disabled."""
    rec = _recorder
    if rec is None:
        return _NULL
    return _Span(rec, name, cat, args or None)


def tracing_enabled() -> bool:
    return _recorder is not None


def current_recorder() -> TraceRecorder | None:
    """The live recorder, or None when tracing is off — for callers
    that add retroactive events (e.g. serve's per-request spans, whose
    duration is only known at retirement) without forcing tracing on
    the way ``enable_tracing`` would."""
    return _recorder


def enable_tracing(*, process_index: int | None = None) -> TraceRecorder:
    """Start recording spans (idempotent: returns the live recorder).

    ``process_index`` defaults to ``jax.process_index()`` when jax is
    already imported, else 0 — span.py itself never imports jax (spans
    must stay usable before/without a backend).
    """
    global _recorder
    if _recorder is not None:
        return _recorder
    if process_index is None:
        import sys

        jax = sys.modules.get("jax")
        process_index = jax.process_index() if jax is not None else 0
    _recorder = TraceRecorder(process_index=process_index)
    return _recorder


def disable_tracing() -> TraceRecorder | None:
    """Stop recording; returns the recorder (with its buffered events)."""
    global _recorder
    rec = _recorder
    _recorder = None
    return rec


def write_trace(path, recorder: TraceRecorder | None = None) -> Path:
    """Serialize the recorder (default: the live one) as Chrome
    trace-event JSON; ``.gz`` suffix gzips — matching the xprof
    ``perfetto_trace.json.gz`` convention."""
    rec = recorder if recorder is not None else _recorder
    if rec is None:
        raise RuntimeError("tracing is not enabled and no recorder given")
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    payload = json.dumps(rec.trace_json())
    if p.suffix == ".gz":
        with gzip.open(p, "wt") as f:
            f.write(payload)
    else:
        p.write_text(payload)
    return p


def _load_trace(path) -> list[dict]:
    p = Path(path)
    opener = gzip.open if p.suffix == ".gz" else open
    with opener(p, "rt") as f:
        tr = json.load(f)
    return tr["traceEvents"] if isinstance(tr, dict) else tr


def merge_chrome_traces(paths, out) -> Path:
    """Concatenate trace-event files (host spans + xprof perfetto device
    slices) into one Chrome trace. Each input keeps its own pid tracks;
    offset alignment is the viewer's job (both sides stamp relative
    timestamps) — the merged file is for eyeballing phase overlap, not
    sub-ms cross-clock skew."""
    events: list[dict] = []
    for path in paths:
        events.extend(_load_trace(path))
    p = Path(out)
    p.parent.mkdir(parents=True, exist_ok=True)
    payload = json.dumps({"traceEvents": events, "displayTimeUnit": "ms"})
    if p.suffix == ".gz":
        with gzip.open(p, "wt") as f:
            f.write(payload)
    else:
        p.write_text(payload)
    return p
