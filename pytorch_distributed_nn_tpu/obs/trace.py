"""Causeway: per-request distributed tracing with cross-process
context propagation.

Every observability layer below this one — registry, flight ring,
watchtower, xray — is host- or replica-scoped: once a request crosses
a disagg handoff, a ``kv_transfer``, a failover re-admission, or a
store-dispatched process boundary, its latency story shatters into
uncorrelated fragments. This module is the causal backbone that keeps
the fragments joined: a :class:`TraceContext` (trace id, per-leg root
span id, parent span id, leg ordinal) is minted at ``Fleet.submit`` /
``Scheduler.submit``, carried on the ticket, and echoed through every
boundary a request can cross:

- scheduler ``_transition`` states (zero-duration marks),
- engine queued/restore/prefill/decode segments (retroactive, from the
  scheduler's lifecycle timestamps — nothing lands in the decode hot
  loop),
- the disagg prefill->decode handoff and the
  ``ops.collectives.kv_transfer`` wire choke point,
- failover re-admission (the re-admitted leg's context links back to
  the original trace via ``parent_id``),
- the :class:`serve.procfleet.ProcessFleet` store wire format:
  ``req/<idx>/<k>`` dispatch records carry ``"trace"`` and worker
  ``prog/`` / ``done/`` echoes return it, so ``fleet_worker.py`` emits
  spans for work it ran into its OWN per-host buffer (published
  through :func:`obs.aggregate.publish_spans`).

Spans are plain dicts — ``{trace, span, parent, leg, segment, host,
t0, t1, ...attrs}`` with unix-epoch second timestamps (monotonic
deltas rebased once per tracer, so one process's spans never skew
against each other; cross-host skew is the store collector's caveat,
same as :func:`obs.span.merge_chrome_traces`). :mod:`obs.critpath`
assembles them into waterfalls and critical paths;
``scripts/obs_trace.py`` renders both.

Arming: ``TPUNN_TRACE=`` (chaos-style spec grammar):

    TPUNN_TRACE=1                          # defaults: sample every request
    TPUNN_TRACE=sample=0.1                 # deterministic 10% sample
    TPUNN_TRACE=tenant=acme                # only tenant "acme"
    TPUNN_TRACE=sample=0.5:slow_ms=250     # keep only traces >= 250ms
                                           # at export time

Sampling is a deterministic hash of the request id (no RNG draw: the
same workload traces the same requests on every host and every rerun —
the byte-identical-replay contract every stream in this codebase
follows). ``slow_ms`` is a retention filter applied at export, not at
emit (a span cannot know its request's final latency).

Design contract (the chaos/watchtower lint rules, enforced by
tests/test_quality.py):

- **Inert when unset.** Every ``on_*`` hook opens with the literal
  ``if _tracer is None: return`` — an unset ``TPUNN_TRACE`` costs one
  global load + one comparison per hook, and performs ZERO registry or
  flight-ring writes (the counters are registered at arm time, not at
  import).
- **Emit-first.** Every span lands in the flight ring before anything
  else sees it (``Tracer._emit``'s first statement) — a crash right
  after a segment completes must still show it post-mortem.

Stdlib-only (no jax, no numpy): ``fleet_worker.py`` imports this
before deciding whether to touch a backend.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import os
import time
from typing import Optional

from pytorch_distributed_nn_tpu.obs import flight
from pytorch_distributed_nn_tpu.obs.registry import get_registry

log = logging.getLogger(__name__)

ENV_TRACE = "TPUNN_TRACE"

# segments a critical path can be attributed to (obs/critpath.py
# priorities live there; this is the emit-side vocabulary)
SEGMENTS = ("queued", "restore", "prefill", "transfer", "failover",
            "decode", "mark")

_ID_BITS = 16  # hex chars of the sha1 digest used for ids


def _digest(text: str) -> str:
    return hashlib.sha1(text.encode()).hexdigest()[:_ID_BITS]


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """The propagated unit: one request's identity on one leg.

    ``trace_id`` names the logical request and never changes across
    handoffs or failovers; ``span_id`` is this leg's root span;
    ``parent_id`` is the previous leg's root span (``""`` for leg 0) —
    the link that keeps a re-admitted leg attached to the original
    trace. Ids derive from the request id by hash, so the same seed
    yields byte-identical trace JSON (the determinism gate
    ``scripts/obs_trace.py --selftest`` pins)."""

    trace_id: str
    span_id: str
    parent_id: str = ""
    leg: int = 0

    def to_wire(self) -> str:
        """Compact store/JSONL wire form — round-trips byte-identically
        through MemStore and the native StoreClient
        (tests/test_store_parity.py)."""
        return (f"{self.trace_id}/{self.span_id}/"
                f"{self.parent_id or '-'}/{self.leg}")

    @classmethod
    def from_wire(cls, wire: str) -> "TraceContext":
        trace_id, span_id, parent, leg = wire.split("/")
        return cls(trace_id=trace_id, span_id=span_id,
                   parent_id="" if parent == "-" else parent,
                   leg=int(leg))

    def child(self) -> "TraceContext":
        """The next leg's context: same trace, leg+1, linked back to
        this leg's root span."""
        leg = self.leg + 1
        return TraceContext(
            trace_id=self.trace_id,
            span_id=_digest(f"{self.trace_id}:{leg}"),
            parent_id=self.span_id, leg=leg)


@dataclasses.dataclass
class TraceConfig:
    """``TPUNN_TRACE`` spec knobs (chaos-grammar ``key=value:...``)."""

    sample: float = 1.0   # deterministic request-id hash sample rate
    tenant: str = ""      # only trace this tenant ("" = all)
    slow_ms: float = 0.0  # export-time retention floor (0 = keep all)
    max_spans: int = 8192  # per-process span buffer bound


_FIELD_TYPES = {f.name: f.type for f in dataclasses.fields(TraceConfig)}


def parse_spec(spec: str) -> TraceConfig:
    """``TPUNN_TRACE`` spec → :class:`TraceConfig`. ``"1"`` / ``"on"``
    mean defaults; otherwise ``:``-separated ``key=value`` overrides.
    Unknown keys raise (a typo'd trace spec must fail loudly, not
    silently trace nothing — the chaos-spec contract)."""
    cfg = TraceConfig()
    spec = (spec or "").strip()
    if spec in ("", "1", "on", "true"):
        return cfg
    for field in filter(None, spec.split(":")):
        key, eq, value = field.partition("=")
        key = key.strip()
        if not eq or key not in _FIELD_TYPES:
            raise ValueError(
                f"unknown trace key {key!r} in {spec!r}; have "
                f"{sorted(_FIELD_TYPES)}")
        try:
            kind = _FIELD_TYPES[key]
            setattr(cfg, key,
                    value if kind in (str, "str")
                    else int(value) if kind in (int, "int")
                    else float(value))
        except ValueError:
            raise ValueError(
                f"bad value for trace key {key!r}: {value!r}") from None
    if not 0.0 <= cfg.sample <= 1.0:
        raise ValueError(f"sample must be in [0, 1], got {cfg.sample}")
    return cfg


class Tracer:
    """Per-process span buffer + the sampling decision. One instance
    per armed process (module singleton); workers and the coordinator
    each run their own and the store collector joins them."""

    def __init__(self, config: TraceConfig, *, rank: int = 0,
                 metrics=None) -> None:
        self.cfg = config
        self.rank = int(rank)
        self.host = f"h{self.rank}"
        self.metrics = metrics  # MetricsLogger | None
        self.spans: list[dict] = []
        # monotonic -> unix rebase, computed ONCE: every span in this
        # process shares the offset, so intra-process deltas are exact
        self._unix_offset = time.time() - time.monotonic()
        # worker-side admit timestamps (request_id -> t_mono), bounded
        # by the span buffer the same requests land in
        self._admits: dict[str, float] = {}
        self._published = 0  # spans already shipped via maybe_publish
        # registered HERE, not at import: TPUNN_TRACE unset must mean
        # zero registry writes (tested)
        reg = get_registry()
        self._c_spans = reg.counter(
            "trace_spans_total", "trace spans emitted",
            labels=("segment",))
        self._c_dropped = reg.counter(
            "trace_dropped_total", "trace spans dropped",
            labels=("reason",))

    # -- sampling ----------------------------------------------------------

    def sampled(self, request_id: str, tenant: str = "default") -> bool:
        """Deterministic: hash(request_id), no RNG — the same request
        id samples identically on every host and every rerun."""
        if self.cfg.tenant and tenant != self.cfg.tenant:
            return False
        if self.cfg.sample >= 1.0:
            return True
        if self.cfg.sample <= 0.0:
            return False
        h = int(hashlib.sha1(request_id.encode()).hexdigest()[:8], 16)
        return h / float(0xFFFFFFFF) < self.cfg.sample

    def mint(self, request_id: str,
             tenant: str = "default") -> Optional[TraceContext]:
        if not self.sampled(request_id, tenant):
            return None
        trace_id = _digest(request_id)
        return TraceContext(trace_id=trace_id,
                            span_id=_digest(f"{trace_id}:0"))

    # -- the span choke point ----------------------------------------------

    def _emit(self, span: dict) -> None:
        """Every span lands in the flight ring FIRST (lint-enforced:
        a crash right after a segment completes must still show it
        post-mortem), then the registry counter, the buffer, and the
        JSONL stream."""
        flight.record("trace", span["segment"],
                      note=f"{span['trace']} leg={span['leg']} "
                           f"{span.get('request_id', '')}")
        self._c_spans.inc(segment=span["segment"])
        if len(self.spans) >= self.cfg.max_spans:
            self._c_dropped.inc(reason="buffer_full")
            return
        self.spans.append(span)
        if self.metrics is not None:
            self.metrics.emit("trace_span", **span)

    def to_unix(self, t_mono: float) -> float:
        return t_mono + self._unix_offset

    def segment(self, ctx: TraceContext, segment: str, t0_mono: float,
                t1_mono: float, **attrs) -> None:
        """Record one duration span for ``ctx`` (timestamps are
        time.monotonic() values from the emitting process)."""
        t0 = self.to_unix(t0_mono)
        t1 = self.to_unix(max(t1_mono, t0_mono))
        span = dict(trace=ctx.trace_id, span=ctx.span_id,
                    parent=ctx.parent_id, leg=ctx.leg,
                    segment=segment, host=self.host,
                    t0=round(t0, 6), t1=round(t1, 6))
        span.update(attrs)
        self._emit(span)

    def mark(self, ctx: TraceContext, name: str, **attrs) -> None:
        """Zero-duration breadcrumb (scheduler state transitions, the
        kv_transfer wire point) — proves the context crossed a
        boundary without claiming any critical-path time."""
        now = self.to_unix(time.monotonic())
        span = dict(trace=ctx.trace_id, span=ctx.span_id,
                    parent=ctx.parent_id, leg=ctx.leg,
                    segment="mark", mark=name, host=self.host,
                    t0=round(now, 6), t1=round(now, 6))
        span.update(attrs)
        self._emit(span)

    # -- export ------------------------------------------------------------

    def export_spans(self) -> list[dict]:
        """The buffer, with the ``slow_ms`` retention filter applied:
        traces whose observed extent is under the floor are dropped
        (and counted) — emit time cannot know a request's final
        latency, so slow-only tracing filters here."""
        if self.cfg.slow_ms <= 0:
            return list(self.spans)
        extent: dict[str, list[float]] = {}
        for s in self.spans:
            lo_hi = extent.setdefault(s["trace"], [s["t0"], s["t1"]])
            lo_hi[0] = min(lo_hi[0], s["t0"])
            lo_hi[1] = max(lo_hi[1], s["t1"])
        keep = {t for t, (lo, hi) in extent.items()
                if (hi - lo) * 1e3 >= self.cfg.slow_ms}
        dropped = len(extent) - len(keep)
        if dropped:
            self._c_dropped.inc(dropped, reason="fast")
        return [s for s in self.spans if s["trace"] in keep]


# ---------------------------------------------------------------------------
# Module singleton + the inert hooks (chaos-style lint contract)
# ---------------------------------------------------------------------------

_tracer: Tracer | None = None


def maybe_init(spec: str | None = None, *, rank: int | None = None,
               metrics=None,
               config: TraceConfig | None = None) -> Tracer | None:
    """Arm the process tracer from ``TPUNN_TRACE`` (or an explicit
    ``spec``/``config``). No-op beyond one env read when unset or
    ``"0"``; idempotent when armed."""
    global _tracer
    if _tracer is not None:
        return _tracer
    spec = os.environ.get(ENV_TRACE) if spec is None else spec
    if not spec or spec == "0":
        return None
    _tracer = Tracer(
        config if config is not None else parse_spec(spec),
        rank=flight.default_rank() if rank is None else rank,
        metrics=metrics,
    )
    log.warning("trace armed: %s (rank %d)", spec, _tracer.rank)
    return _tracer


def enabled() -> bool:
    return _tracer is not None


def tracer() -> Tracer | None:
    return _tracer


def reset() -> None:
    """Disarm (test isolation)."""
    global _tracer
    _tracer = None


def attach_metrics(metrics) -> None:
    """Late-bind the JSONL sink (engines/fleets construct after
    arming). Not a hot-path hook, but still inert-guarded."""
    if _tracer is None:
        return
    if metrics is not None:
        _tracer.metrics = metrics


def export_spans() -> list[dict]:
    """This process's spans (slow_ms filter applied); [] when unarmed."""
    if _tracer is None:
        return []
    return _tracer.export_spans()


# -- propagation hooks (every one: inert fast path, lint-enforced) ----------


def on_submit(request_id: str,
              tenant: str = "default") -> Optional[TraceContext]:
    """Mint a context at admission (``Fleet.submit`` /
    ``ProcessFleet.submit`` / standalone ``Scheduler.submit``).
    None when unarmed or the request is not sampled."""
    if _tracer is None:
        return None
    return _tracer.mint(request_id, tenant)


def on_resubmit(ctx) -> Optional[TraceContext]:
    """The failover / handoff boundary: the next leg's context, linked
    back to the original trace (``parent_id`` = the previous leg's
    root span). None when unarmed or ``ctx`` is None."""
    if _tracer is None:
        return None
    if ctx is None:
        return None
    return ctx.child()


def on_transition(ctx, state: str, request_id: str = "") -> None:
    """Scheduler ``_transition`` breadcrumb — every state change of a
    traced request leaves a mark (lint-pinned to the one choke
    point)."""
    if _tracer is None:
        return
    if ctx is None:
        return
    _tracer.mark(ctx, f"state:{state}", request_id=request_id)


def on_segment(ctx, segment: str, t0_mono: float, t1_mono: float,
               **attrs) -> None:
    """One attributed slice of a traced request's life (queued /
    restore / prefill / transfer / failover / decode), timestamps in
    the emitting process's ``time.monotonic()``."""
    if _tracer is None:
        return
    if ctx is None:
        return
    _tracer.segment(ctx, segment, t0_mono, t1_mono, **attrs)


def on_transfer(ctx, *, src: str, dst: str, nbytes: int) -> None:
    """The ``ops.collectives.kv_transfer`` wire choke point: a mark
    that the context rode the KV stream (the duration lands as a
    ``transfer`` segment from ``DisaggFleet._stream_blocks``, which
    owns the wall clock around the wire)."""
    if _tracer is None:
        return
    if ctx is None:
        return
    _tracer.mark(ctx, "kv_transfer", src=src, dst=dst, nbytes=int(nbytes))


def on_worker_admit(rec: dict, *, host: int) -> None:
    """Worker-process side (fleet_worker.py): a dispatch record pulled
    from ``req/<idx>/<k>`` enters the backend — stamp the admit time
    so the completion hook can span the remote leg."""
    if _tracer is None:
        return
    if "trace" not in rec:
        return
    _tracer._admits[str(rec.get("request_id", ""))] = time.monotonic()


def on_worker_done(rec: dict, tokens: list, status: str, *,
                   host: int) -> None:
    """Worker-process side: the request finished on this replica —
    emit the remote decode span into THIS process's buffer (its own
    per-host ring; the store collector joins it with the
    coordinator's)."""
    if _tracer is None:
        return
    if "trace" not in rec:
        return
    try:
        ctx = TraceContext.from_wire(str(rec["trace"]))
    except (ValueError, TypeError):
        _tracer._c_dropped.inc(reason="bad_wire")
        return
    rid = str(rec.get("request_id", ""))
    now = time.monotonic()
    t0 = _tracer._admits.pop(rid, now)
    _tracer.segment(ctx, "decode", t0, now, request_id=rid,
                    host_index=int(host), tokens=len(tokens),
                    status=status)


def maybe_publish(client, *, rank: int) -> bool:
    """Publish this process's spans through the store (the
    :func:`obs.aggregate.publish_spans` transport). Inert no-op when
    unarmed or nothing new since the last publish; never raises into
    the serve loop."""
    if _tracer is None:
        return False
    if not _tracer.spans:
        return False
    n = len(_tracer.spans)
    if n == _tracer._published:
        return False
    from pytorch_distributed_nn_tpu.obs import aggregate
    from pytorch_distributed_nn_tpu.runtime import failure

    # counted retry (store_errors_total{op="trace_publish"}): a blip
    # retries within the bounded deadline; a real outage degrades to a
    # dropped publish the NEXT tick retries naturally — the daemon
    # thread calling this can never die of an uncounted store error
    out = failure.store_call(
        lambda: aggregate.publish_spans(
            client, rank=rank, spans=_tracer.export_spans()),
        op="trace_publish", deadline_s=0.5, fallback=None)
    if out is None:
        _tracer._c_dropped.inc(reason="store_error")
        log.warning("trace span publish failed past deadline")
        return False
    _tracer._published = n
    return True


# ---------------------------------------------------------------------------
# Chrome trace-event bridge (obs/span.py merge compatibility)
# ---------------------------------------------------------------------------


def spans_to_chrome(spans: list[dict], *,
                    pid: int | None = None) -> list[dict]:
    """Span dicts → Chrome trace events (``ph:"X"``, µs since the unix
    epoch) whose ``args`` carry the full span — so a file written from
    these merges through :func:`obs.span.merge_chrome_traces` and
    :func:`obs.critpath.spans_from_chrome` can reconstruct the spans
    from the merged timeline."""
    out = []
    for s in spans:
        host_pid = pid
        if host_pid is None:
            h = str(s.get("host", "h0"))
            digits = "".join(c for c in h if c.isdigit())
            host_pid = int(digits) if digits else 0
        out.append({
            "name": f"{s['trace'][:8]}/{s['segment']}",
            "cat": "trace", "ph": "X",
            "ts": s["t0"] * 1e6,
            "dur": max(s["t1"] - s["t0"], 0.0) * 1e6,
            "pid": host_pid, "tid": s.get("leg", 0),
            "args": dict(s),
        })
    return out
