from pytorch_distributed_nn_tpu.utils.profiling import (  # noqa: F401
    StepTimer,
    bus_bandwidth,
    time_steps,
    xprof_trace,
)
from pytorch_distributed_nn_tpu.utils.metrics import (  # noqa: F401
    MetricsLogger,
)
