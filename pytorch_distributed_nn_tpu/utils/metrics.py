"""Structured metrics logging with rank-0 aggregation.

The reference prints loss/throughput with bare ``print`` on every rank
(SURVEY.md §5 "Metrics/logging" row). Here: a per-host structured JSONL
writer where only the coordinator (process 0) emits by default — the
analogue of the ``if rank == 0: print`` idiom, but machine-readable and
in the BASELINE.json metric schema so benchmark runs can fill
``published`` directly.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from pathlib import Path
from typing import Any, IO

import jax

log = logging.getLogger(__name__)


class MetricsLogger:
    """JSONL metric stream: one dict per event.

    ``all_hosts=False`` (default) silences non-coordinator processes —
    call sites never need the ``if rank == 0`` guard.
    """

    def __init__(self, path: str | Path | None = None, *,
                 all_hosts: bool = False,
                 stream: IO | None = None) -> None:
        self.enabled = all_hosts or jax.process_index() == 0
        self._fh: IO | None = None
        if not self.enabled:
            return
        if path is not None:
            p = Path(path)
            p.parent.mkdir(parents=True, exist_ok=True)
            self._fh = p.open("a")
        else:
            self._fh = stream or sys.stdout

    def emit(self, event: str, **fields: Any) -> None:
        if not self.enabled:
            return
        rec = {"event": event, "time": time.time(),
               "process": jax.process_index(), **fields}
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def emit_benchmark(self, metric: str, value: float, unit: str,
                       vs_baseline: float | None = None,
                       **extra: Any) -> dict:
        """The BASELINE.json schema line the driver's bench harness
        expects (plus any extra fields, e.g. mfu); returned so callers
        can also print it bare."""
        rec = {"metric": metric, "value": value, "unit": unit,
               "vs_baseline": vs_baseline, **extra}
        self.emit("benchmark", **rec)
        return rec

    def close(self) -> None:
        if self._fh is not None and self._fh not in (sys.stdout,
                                                     sys.stderr):
            self._fh.close()
            self._fh = None  # idempotent: double-close is a no-op
            self.enabled = False  # emit after close: silent no-op

    # context manager: `with MetricsLogger(path) as m:` guarantees the
    # file handle closes on exceptions (Trainer rides this via its own
    # __enter__/__exit__)
    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
