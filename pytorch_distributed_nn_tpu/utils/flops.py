"""Analytic model-FLOPs counting and MFU.

MFU (model FLOPs utilization) = achieved model FLOPs/s divided by the
chip's peak dense FLOPs/s. "Model FLOPs" is the *algorithmic* cost of a
training step — forward FLOPs x 3 (the backward pass costs ~2x forward
for matmul/conv networks: one pass for dL/dW, one for dL/dx) — counted
on the un-rematerialized forward. Recompute inserted by
``jax.checkpoint`` is real hardware work but NOT useful model work, so
it does not count (the PaLM-appendix / MLPerf convention); MFU therefore
penalizes remat exactly as it should.

Forward FLOPs come from XLA's own cost model applied to the lowered
(pre-optimization) HLO of the forward pass: the compiler literally
counts every conv and dot at the traced shapes. This is the "counted
convs" number for ResNet and agrees with the ``6N + 12*L*T^2*d`` closed
form for transformer LMs (cross-checked in tests/test_flops.py). Note
XLA counts a multiply-accumulate as 2 FLOPs, so ResNet-50 fwd at 224^2
is ~8.2 GFLOPs here, not the "4.1 GFLOPs" MAC-count papers quote.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Peak dense bf16 FLOP/s per chip, keyed by substring of
# ``device.device_kind`` (lowercased). Public figures from the TPU
# product pages / "How to Scale Your Model".
PEAK_BF16_FLOPS = {
    "v6e": 918e12,
    "v6 lite": 918e12,
    "v5p": 459e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5litepod": 197e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 46e12,
}


class CostModelUnavailable(RuntimeError):
    """No reachable in-process backend implements the HLO cost model
    (the axon TPU plugin has none, and JAX_PLATFORMS may be pinned so
    no cpu backend is registered either)."""


def peak_flops_per_chip(device=None, dtype=None) -> float | None:
    """Peak dense FLOP/s for ``device`` (default: jax.devices()[0]) at
    ``dtype`` (default bf16), or None when the chip is unknown (CPU test
    platform). TPUs run f32 matmuls at half the bf16 MXU rate, so an
    f32-compute model's MFU must be judged against the f32 peak."""
    if device is None:
        device = jax.devices()[0]
    kind = (getattr(device, "device_kind", "") or "").lower()
    for key, val in PEAK_BF16_FLOPS.items():
        if key in kind:
            if dtype is not None and jnp.dtype(dtype) == jnp.float32:
                return val / 2.0
            return val
    return None


def fwd_flops(model, x_shape: tuple, x_dtype) -> float:
    """XLA-counted forward FLOPs of ``model.apply`` on one batch of
    shape ``x_shape``.

    Lowering is fully abstract (no params are materialized, nothing
    executes); the count is exact for the traced shapes and scales
    linearly in the leading batch dim for every model here, so callers
    can count at batch 1 and multiply.
    """
    x = jax.ShapeDtypeStruct(tuple(x_shape), x_dtype)

    def init():
        return model.init(jax.random.key(0),
                          jnp.zeros(x.shape, x.dtype), train=False)

    variables = jax.eval_shape(init)

    def fwd(v, xb):
        return model.apply(v, xb, train=False)

    lowered = jax.jit(fwd).lower(variables, x)
    analysis = lowered.cost_analysis()
    if not isinstance(analysis, dict) or "flops" not in analysis:
        # Some PJRT plugins (the axon TPU tunnel) implement no
        # pre-compile HLO cost model and return None (ONCHIP_r03 first
        # sweep: every preset's mfu was null). The count is a property
        # of the traced HLO, not the backend, so redo the lowering on
        # the host CPU backend — same trace, same shapes, same convs
        # and dots — and read the cost model there. (CostModelUnavailable
        # when no cpu backend is registered in-process — JAX_PLATFORMS
        # pinned to the TPU plugin — which train_flops_per_sample
        # handles with a JAX_PLATFORMS=cpu subprocess.)
        try:
            cpu = jax.local_devices(backend="cpu")[0]
        except RuntimeError as e:
            raise CostModelUnavailable(str(e)) from e
        with jax.default_device(cpu):
            analysis = jax.jit(fwd).lower(variables, x).cost_analysis()
    if not isinstance(analysis, dict) or "flops" not in analysis:
        raise CostModelUnavailable(
            f"XLA cost analysis returned no flops: {analysis!r}"
        )
    return float(analysis["flops"])


# Input shapes of the synthetic/token datasets, derivable from config
# alone — counting FLOPs must not re-read a multi-GB data file just for
# .spec (tests cross-check these against the real dataset specs).
_IMAGE_SPECS = {
    "mnist": (28, 28),
    "cifar10": (32, 32, 3),
    "imagenet_synthetic": (224, 224, 3),
}
_TOKEN_DATASETS = ("lm_synthetic", "mlm_synthetic", "token_file")


def _input_spec(cfg):
    import numpy as np

    if cfg.data.dataset in _IMAGE_SPECS:
        return _IMAGE_SPECS[cfg.data.dataset], np.float32
    if cfg.data.dataset in _TOKEN_DATASETS:
        return (cfg.data.seq_len,), np.int32
    # file readers with format-fixed (or config-derived) shapes — never
    # rescan an ImageNet-sized tree or reload a corpus just for .spec
    if cfg.data.dataset == "cifar10_bin":
        return (32, 32, 3), np.float32
    if cfg.data.dataset == "mnist_idx":
        # idx files encode arbitrary dims — probe the real header when a
        # path is configured (a wrong hardcode would silently mis-scale
        # MFU); (28, 28) only as the no-path default
        if cfg.data.path:
            from pathlib import Path

            from pytorch_distributed_nn_tpu.data.readers import (
                _find_one,
                read_idx_header,
            )

            imgs = _find_one(Path(cfg.data.path), "train-images-idx3-ubyte")
            if imgs is not None:
                _, dims = read_idx_header(imgs)
                return tuple(dims[1:]), np.float32
        return (28, 28), np.float32  # the idx standard layout
    if cfg.data.dataset == "image_folder":
        s = cfg.data.image_size
        return (s, s, 3), np.float32
    # array_file and friends: the shape lives in the file/config
    from pytorch_distributed_nn_tpu.data import get_dataset

    spec = get_dataset(
        cfg.data.dataset, seed=0, batch_size=1,
        seq_len=cfg.data.seq_len, vocab_size=cfg.data.vocab_size,
        path=cfg.data.path, token_dtype=cfg.data.token_dtype,
        image_size=cfg.data.image_size,
    ).spec
    return spec.x_shape, spec.x_dtype


def train_flops_per_sample(cfg, _subprocess_ok: bool = True) -> float:
    """Analytic training FLOPs for ONE sample of ``cfg``'s model on
    ``cfg``'s data shapes: 3 x forward (see module docstring).

    For LMs a "sample" is one full sequence of ``cfg.data.seq_len``
    tokens, matching how the bench counts samples/sec.

    When the in-process count fails because no backend with a cost
    model is reachable (JAX_PLATFORMS pinned to the axon TPU plugin,
    which has none, with no cpu registered), the count reruns in a
    JAX_PLATFORMS=cpu subprocess — pure host work, a few seconds.
    """
    from pytorch_distributed_nn_tpu.models import get_model

    import dataclasses

    # Count the *algorithm*, not the benched implementation: remat off
    # (recompute isn't model work), and dense-XLA attention — a Pallas
    # flash/ring kernel is a custom call the HLO cost model scores as 0
    # FLOPs, which would silently drop the dominant T^2 term at long
    # context.
    model_cfg = dataclasses.replace(
        cfg.model, remat=False,
        extra={**cfg.model.extra, "attn_impl": "xla"},
    )
    model = get_model(model_cfg)
    x_shape, x_dtype = _input_spec(cfg)
    try:
        return 3.0 * fwd_flops(model, (1, *x_shape), x_dtype)
    except CostModelUnavailable:
        # only the missing-cost-model case is retried out of process;
        # genuine lowering/tracing failures propagate with their full
        # in-process traceback
        if not _subprocess_ok:
            raise
        return _train_flops_subprocess(cfg)


def _train_flops_subprocess(cfg) -> float:
    """train_flops_per_sample in a fresh JAX_PLATFORMS=cpu interpreter
    (the config pickles; the model/trace does not need to)."""
    import os
    import pickle
    import subprocess
    import sys
    import tempfile

    fd, path = tempfile.mkstemp(suffix=".pkl")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(cfg, f)
        code = (
            "import os, pickle, sys\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "from pytorch_distributed_nn_tpu.runtime.platform import "
            "apply_platform_overrides\n"
            "apply_platform_overrides()\n"
            "from pytorch_distributed_nn_tpu.utils.flops import "
            "train_flops_per_sample\n"
            f"cfg = pickle.load(open({path!r}, 'rb'))\n"
            "print('FLOPS_PER_SAMPLE=%r'\n"
            "      % train_flops_per_sample(cfg, _subprocess_ok=False))\n"
        )
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=600, cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
        )
        for line in r.stdout.splitlines():
            if line.startswith("FLOPS_PER_SAMPLE="):
                return float(line.split("=", 1)[1])
        raise RuntimeError(
            f"subprocess FLOPs count failed (rc {r.returncode}): "
            f"{r.stderr[-500:]}"
        )
    finally:
        os.unlink(path)


def lm_train_flops_per_token(n_params: int, n_layers: int,
                             seq_len: int, d_model: int) -> float:
    """The 6N + 12*L*T*d closed form (PaLM appendix B): per-token
    training FLOPs of a dense transformer LM with N matmul-participating
    params. Used as the independent cross-check of the XLA count."""
    return 6.0 * n_params + 12.0 * n_layers * seq_len * d_model


def mfu(samples_per_sec_chip: float, flops_per_sample: float,
        device=None, dtype=None) -> float | None:
    """Achieved / peak FLOPs for one chip; None off-TPU. ``dtype`` is
    the model's COMPUTE dtype (``model.dtype``): f32 runs against the
    halved f32 peak (see peak_flops_per_chip)."""
    peak = peak_flops_per_chip(device, dtype=dtype)
    if peak is None:
        return None
    return samples_per_sec_chip * flops_per_sample / peak
