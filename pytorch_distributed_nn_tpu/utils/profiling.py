"""Tracing / profiling hooks.

The reference has none of its own — the ecosystem answer is
``torch.profiler`` + NCCL debug counters (SURVEY.md §5 "Tracing/profiling"
row). TPU-native equivalents:

- :func:`xprof_trace` — ``jax.profiler`` capture to a TensorBoard/XProf
  log dir (set ``TrainConfig.profile_dir``);
- :class:`StepTimer` / :func:`time_steps` — honest per-step wall timing
  (``block_until_ready`` fencing, so async dispatch can't flatter the
  numbers);
- :func:`bus_bandwidth` — the BASELINE "grad-allreduce bus-bw" metric:
  trace-time wire-byte accounting from :mod:`ops.collectives` divided by
  measured step time.
"""

from __future__ import annotations

import contextlib
import dataclasses
import glob
import gzip
import json
import os
import re
import time
from typing import Callable, Sequence

import jax
import numpy as np

from pytorch_distributed_nn_tpu.ops import collectives as cc


@contextlib.contextmanager
def xprof_trace(log_dir: str, *, perfetto: bool = False):
    """Capture an XProf/TensorBoard trace of the enclosed steps.
    ``perfetto=True`` additionally writes ``perfetto_trace.json.gz``
    (Chrome trace-event JSON), which :func:`collective_trace_seconds`
    parses — XProf's xplane protos need the TensorBoard profile plugin
    that this container doesn't ship."""
    jax.profiler.start_trace(log_dir, create_perfetto_trace=perfetto)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# Collective-op slice names across backends: TPU emits fusion/op names
# like 'all-reduce.3' / 'all-reduce-start'; XLA CPU emits the HLO name
# ('psum_invariant.7', 'collective-permute', ...). Python-level slices
# ('$file.py:123 fn') and paired 'end: <op>' markers are excluded.
_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|all-to-all|reduce-scatter|"
    r"collective-permute|collective-broadcast|psum|ppermute|"
    r"allreduce|allgather)", re.IGNORECASE,
)


@dataclasses.dataclass
class CollectiveTrace:
    """Profile-derived collective time (see collective_trace_seconds)."""

    total_s: float  # summed slice duration across ALL device tracks
    per_device_s: float  # total_s / device participant count
    n_events: int
    names: dict[str, float]  # per-op-name seconds (diagnostics)


def collective_trace_seconds(log_dir: str,
                             world: int) -> CollectiveTrace | None:
    """Parse the newest perfetto trace under ``log_dir`` and sum the
    durations of collective-op slices (BASELINE.json bus-bw metric,
    VERDICT r2 Missing #3: bus bandwidth derived *from profile*, not
    from wire-byte bookkeeping alone).

    Each participating device contributes its own slice per executed
    collective, so ``per_device_s = total / world`` is the average time
    one device spent inside collectives. Async pairs (TPU
    'all-reduce-start'/'-done') both count — start covers the transfer
    window, done the wait — so the figure is an upper bound on wire
    occupancy; the cross-check against analytic wire bytes in
    ``bench.py --metric bus_bw`` reports both. Returns None when no
    trace file or no collective slices are found (e.g. world == 1 —
    XLA elides the collectives entirely)."""
    paths = sorted(glob.glob(
        os.path.join(log_dir, "**", "perfetto_trace.json.gz"),
        recursive=True,
    ))
    if not paths:
        return None
    with gzip.open(paths[-1]) as f:
        tr = json.load(f)
    events = tr["traceEvents"] if isinstance(tr, dict) else tr
    rx = _COLLECTIVE_RE
    total_us = 0.0
    names: dict[str, float] = {}
    n = 0
    for e in events:
        name = e.get("name", "")
        if (e.get("ph") != "X" or name.startswith("$")
                or name.startswith("end: ") or not rx.search(name)):
            continue
        dur = float(e.get("dur", 0.0))
        total_us += dur
        names[name] = names.get(name, 0.0) + dur / 1e6
        n += 1
    if n == 0:
        return None
    return CollectiveTrace(
        total_s=total_us / 1e6,
        per_device_s=total_us / 1e6 / max(world, 1),
        n_events=n,
        names=names,
    )


class StepTimer:
    """Wall-clock per-step timer with device fencing."""

    def __init__(self) -> None:
        self.times: list[float] = []
        self._t0: float | None = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, *fence) -> float:
        """Record one step; ``fence`` arrays are blocked on first."""
        if fence:
            jax.block_until_ready(fence)
        dt = time.perf_counter() - self._t0
        self.times.append(dt)
        return dt

    def summary(self) -> dict[str, float]:
        if not self.times:
            # an unstarted/empty timer must summarize, not crash
            # (np.percentile([]) raises): zeros, steps=0
            return {"steps": 0, "mean_s": 0.0, "p50_s": 0.0,
                    "p95_s": 0.0, "total_s": 0.0}
        ts = np.array(self.times)
        return {
            "steps": len(ts),
            "mean_s": float(ts.mean()),
            "p50_s": float(np.percentile(ts, 50)),
            "p95_s": float(np.percentile(ts, 95)),
            "total_s": float(ts.sum()),
        }


def time_steps(step_fn: Callable, args_fn: Callable[[int], tuple], *,
               iters: int, warmup: int = 3,
               carry_state: bool = True) -> StepTimer:
    """Time ``iters`` executions of ``step_fn``. ``args_fn(i)`` yields the
    per-step ``(state, *batch)`` args; when ``carry_state`` the returned
    state threads into the next call (the real training pattern)."""
    state, *batch = args_fn(0)
    for i in range(warmup):
        out = step_fn(state, *batch)
        state = out[0] if carry_state else state
        _, *batch = args_fn(i + 1)
    jax.block_until_ready(state)
    timer = StepTimer()
    for i in range(iters):
        timer.start()
        out = step_fn(state, *batch)
        new_state = out[0] if carry_state else state
        timer.stop(new_state)
        state = new_state
        _, *batch = args_fn(warmup + i + 1)
    return timer


@dataclasses.dataclass
class BusBandwidth:
    wire_gbps: float  # GB/s of link traffic per device
    wire_bytes_per_step: float
    step_s: float
    records: int


def bus_bandwidth(records: Sequence[cc.CommRecord],
                  step_s: float) -> BusBandwidth:
    """Ring-accounted wire bytes per device / measured step time — the
    comparable of NCCL's busbw (nccl-tests definition)."""
    wire = cc.wire_bytes(records)
    return BusBandwidth(
        wire_gbps=wire / step_s / 1e9 if step_s > 0 else 0.0,
        wire_bytes_per_step=wire,
        step_s=step_s,
        records=len(records),
    )
