"""Tracing / profiling hooks — absorbed into :mod:`obs.xray`.

The primitives that used to live here (``xprof_trace`` capture,
``StepTimer``/``time_steps`` fenced wall timing, the perfetto
collective-slice parser, ``bus_bandwidth``) are now part of the Xray
subsystem (:mod:`pytorch_distributed_nn_tpu.obs.xray`), which adds
anomaly-triggered capture, per-op attribution, and compile telemetry
on top of them. This shim re-exports the original names so existing
imports (bench.py, tests, notebooks) keep working unchanged.
"""

from __future__ import annotations

from pytorch_distributed_nn_tpu.obs.xray import (  # noqa: F401
    _COLLECTIVE_RE,
    BusBandwidth,
    CollectiveTrace,
    StepTimer,
    bus_bandwidth,
    collective_trace_seconds,
    time_steps,
    xprof_trace,
)

__all__ = [
    "BusBandwidth",
    "CollectiveTrace",
    "StepTimer",
    "bus_bandwidth",
    "collective_trace_seconds",
    "time_steps",
    "xprof_trace",
]
