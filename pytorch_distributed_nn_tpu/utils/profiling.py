"""Tracing / profiling hooks.

The reference has none of its own — the ecosystem answer is
``torch.profiler`` + NCCL debug counters (SURVEY.md §5 "Tracing/profiling"
row). TPU-native equivalents:

- :func:`xprof_trace` — ``jax.profiler`` capture to a TensorBoard/XProf
  log dir (set ``TrainConfig.profile_dir``);
- :class:`StepTimer` / :func:`time_steps` — honest per-step wall timing
  (``block_until_ready`` fencing, so async dispatch can't flatter the
  numbers);
- :func:`bus_bandwidth` — the BASELINE "grad-allreduce bus-bw" metric:
  trace-time wire-byte accounting from :mod:`ops.collectives` divided by
  measured step time.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Callable, Sequence

import jax
import numpy as np

from pytorch_distributed_nn_tpu.ops import collectives as cc


@contextlib.contextmanager
def xprof_trace(log_dir: str):
    """Capture an XProf/TensorBoard trace of the enclosed steps."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock per-step timer with device fencing."""

    def __init__(self) -> None:
        self.times: list[float] = []
        self._t0: float | None = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, *fence) -> float:
        """Record one step; ``fence`` arrays are blocked on first."""
        if fence:
            jax.block_until_ready(fence)
        dt = time.perf_counter() - self._t0
        self.times.append(dt)
        return dt

    def summary(self) -> dict[str, float]:
        ts = np.array(self.times)
        return {
            "steps": len(ts),
            "mean_s": float(ts.mean()),
            "p50_s": float(np.percentile(ts, 50)),
            "p95_s": float(np.percentile(ts, 95)),
            "total_s": float(ts.sum()),
        }


def time_steps(step_fn: Callable, args_fn: Callable[[int], tuple], *,
               iters: int, warmup: int = 3,
               carry_state: bool = True) -> StepTimer:
    """Time ``iters`` executions of ``step_fn``. ``args_fn(i)`` yields the
    per-step ``(state, *batch)`` args; when ``carry_state`` the returned
    state threads into the next call (the real training pattern)."""
    state, *batch = args_fn(0)
    for i in range(warmup):
        out = step_fn(state, *batch)
        state = out[0] if carry_state else state
        _, *batch = args_fn(i + 1)
    jax.block_until_ready(state)
    timer = StepTimer()
    for i in range(iters):
        timer.start()
        out = step_fn(state, *batch)
        new_state = out[0] if carry_state else state
        timer.stop(new_state)
        state = new_state
        _, *batch = args_fn(warmup + i + 1)
    return timer


@dataclasses.dataclass
class BusBandwidth:
    wire_gbps: float  # GB/s of link traffic per device
    wire_bytes_per_step: float
    step_s: float
    records: int


def bus_bandwidth(records: Sequence[cc.CommRecord],
                  step_s: float) -> BusBandwidth:
    """Ring-accounted wire bytes per device / measured step time — the
    comparable of NCCL's busbw (nccl-tests definition)."""
    wire = cc.wire_bytes(records)
    return BusBandwidth(
        wire_gbps=wire / step_s / 1e9 if step_s > 0 else 0.0,
        wire_bytes_per_step=wire,
        step_s=step_s,
        records=len(records),
    )
