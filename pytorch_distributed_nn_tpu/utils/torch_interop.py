"""PyTorch ↔ framework weight interop.

The reference's users hold `state_dict()` checkpoints (torch `nn.Module`
weights — SURVEY.md §5 "Checkpoint / resume" row names `torch.save/load`
as the reference's only persistence). Migration therefore needs a weight
bridge, not just an API map (docs/migration.md): these converters move
weights between torch layouts and this framework's flax param trees.

Conventions bridged:

- torch ``nn.Linear.weight`` is ``(out, in)``; flax ``Dense.kernel`` is
  ``(in, out)`` — transposed.
- attention projections here are ``DenseGeneral`` kernels shaped
  ``(d_model, heads, head_dim)`` (q/k/v) and ``(heads, head_dim,
  d_model)`` (out); torch/HF fuse heads into one matrix row dim.
- rotary halves: both sides use the split-half convention (HF
  ``rotate_half``; :func:`..nn.attention.rotary_embedding`), so q/k need
  **no** permutation — weights map 1:1.

torch is imported lazily: the framework itself never depends on it, the
bridge only needs it when called (and accepts numpy-valued state dicts
too, e.g. one loaded on a host without torch).
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np


def to_numpy(x) -> np.ndarray:
    """torch tensor | numpy array → numpy (detached, CPU, contiguous)."""
    if hasattr(x, "detach"):  # torch tensor without importing torch
        x = x.detach().cpu().numpy()
    return np.ascontiguousarray(x)


def linear_kernel(weight) -> np.ndarray:
    """torch Linear weight (out, in) → flax Dense kernel (in, out)."""
    return to_numpy(weight).T


def _heads_in_kernel(weight, heads: int, head_dim: int) -> np.ndarray:
    """(H*Dh, D) q/k/v projection → DenseGeneral kernel (D, H, Dh)."""
    w = to_numpy(weight)
    d_model = w.shape[1]
    return w.T.reshape(d_model, heads, head_dim)


class _TrackingDict:
    """Read-through view of a state_dict that records consumed keys, so
    converters can fail loudly on tensors their layout never mapped."""

    def __init__(self, sd: Mapping[str, Any]):
        self.sd = sd
        self.consumed: set[str] = set()

    def __getitem__(self, key):
        self.consumed.add(key)
        return self.sd[key]

    def get(self, key, default=None):
        if key in self.sd:
            return self[key]
        return default

    def check_consumed(self, ignorable: tuple[str, ...]) -> None:
        leftover = [k for k in self.sd if k not in self.consumed
                    and not any(frag in k for frag in ignorable)]
        if leftover:
            raise ValueError(
                f"state_dict tensors this layout does not map (model "
                f"variant mismatch?): {sorted(leftover)[:8]}"
            )


def _ln_leaf(sd, prefix: str) -> dict:
    """HF LayerNorm ``{prefix}.weight/.bias`` → flax {scale, bias}."""
    return {"scale": to_numpy(sd[prefix + ".weight"]),
            "bias": to_numpy(sd[prefix + ".bias"])}


def _dense_leaf(sd, prefix: str) -> dict:
    """HF Linear ``{prefix}.weight/.bias`` → flax Dense leaf."""
    return {"kernel": linear_kernel(sd[prefix + ".weight"]),
            "bias": to_numpy(sd[prefix + ".bias"])}


def _heads_in_leaf(sd, prefix: str, heads: int, head_dim: int) -> dict:
    """HF per-head input projection → DenseGeneral (D, H, Dh) leaf."""
    return {
        "kernel": _heads_in_kernel(sd[prefix + ".weight"], heads,
                                   head_dim),
        "bias": to_numpy(sd[prefix + ".bias"]).reshape(heads, head_dim),
    }


def _heads_out_kernel(weight, heads: int, head_dim: int) -> np.ndarray:
    """(D, H*Dh) out projection → DenseGeneral kernel (H, Dh, D)."""
    w = to_numpy(weight)
    d_model = w.shape[0]
    return w.T.reshape(heads, head_dim, d_model)


def llama_params_from_torch(
    state_dict: Mapping[str, Any],
    *,
    num_layers: int,
    num_heads: int,
    num_kv_heads: int,
) -> dict:
    """HF ``LlamaForCausalLM.state_dict()`` → params for models/llama.py.

    Key layout bridged (HF side): ``model.embed_tokens``, per layer
    ``model.layers.{i}.{input_layernorm, self_attn.{q,k,v,o}_proj,
    post_attention_layernorm, mlp.{gate,up,down}_proj}``, ``model.norm``,
    ``lm_head`` (untied, as Llama-3 ships). Raises KeyError on missing
    keys — a truncated checkpoint should fail loudly, not half-load.
    """
    tracked = _TrackingDict(state_dict)
    embed = to_numpy(tracked["model.embed_tokens.weight"])  # (V, D)
    d_model = embed.shape[1]
    if d_model % num_heads:
        raise ValueError(f"d_model {d_model} % num_heads {num_heads} != 0")
    head_dim = d_model // num_heads

    params: dict = {"tok_embed": {"embedding": embed}}
    for i in range(num_layers):
        p = f"model.layers.{i}."
        params[f"layer{i}"] = {
            "attn_norm": {"scale": to_numpy(
                tracked[p + "input_layernorm.weight"])},
            "attn": {
                "query": {"kernel": _heads_in_kernel(
                    tracked[p + "self_attn.q_proj.weight"], num_heads,
                    head_dim)},
                "key": {"kernel": _heads_in_kernel(
                    tracked[p + "self_attn.k_proj.weight"], num_kv_heads,
                    head_dim)},
                "value": {"kernel": _heads_in_kernel(
                    tracked[p + "self_attn.v_proj.weight"], num_kv_heads,
                    head_dim)},
                "out": {"kernel": _heads_out_kernel(
                    tracked[p + "self_attn.o_proj.weight"], num_heads,
                    head_dim)},
            },
            "mlp_norm": {"scale": to_numpy(
                tracked[p + "post_attention_layernorm.weight"])},
            "gate_proj": {"kernel": linear_kernel(
                tracked[p + "mlp.gate_proj.weight"])},
            "up_proj": {"kernel": linear_kernel(
                tracked[p + "mlp.up_proj.weight"])},
            "down_proj": {"kernel": linear_kernel(
                tracked[p + "mlp.down_proj.weight"])},
        }
    params["final_norm"] = {"scale": to_numpy(tracked["model.norm.weight"])}
    lm_head = tracked.get("lm_head.weight")
    if lm_head is None:  # tied-embedding checkpoints (llama-2 style)
        lm_head = embed
    params["lm_head"] = {"kernel": to_numpy(lm_head).T}

    # Fail loudly on anything the layout above didn't consume (e.g.
    # attention biases from a Qwen-style attention_bias=True checkpoint):
    # silently dropping learned tensors would produce wrong logits with
    # no error. Non-learned rotary buffers are the one known exception.
    tracked.check_consumed(ignorable=("rotary_emb",))
    return params


def llama_params_to_torch(params: Mapping[str, Any]) -> dict:
    """Inverse of :func:`llama_params_from_torch`: params →
    HF-layout state dict of torch tensors."""
    t = _tt  # shared copy=True/from_numpy helper

    out = {
        "model.embed_tokens.weight": t(params["tok_embed"]["embedding"]),
        "model.norm.weight": t(params["final_norm"]["scale"]),
        "lm_head.weight": t(np.asarray(params["lm_head"]["kernel"]).T),
    }
    i = 0
    while f"layer{i}" in params:
        layer = params[f"layer{i}"]
        p = f"model.layers.{i}."
        attn = layer["attn"]
        d_model = np.asarray(attn["query"]["kernel"]).shape[0]

        def fuse_in(kernel):  # (D, H, Dh) → (H*Dh, D)
            return t(np.asarray(kernel).reshape(d_model, -1).T)

        out[p + "input_layernorm.weight"] = t(layer["attn_norm"]["scale"])
        out[p + "self_attn.q_proj.weight"] = fuse_in(attn["query"]["kernel"])
        out[p + "self_attn.k_proj.weight"] = fuse_in(attn["key"]["kernel"])
        out[p + "self_attn.v_proj.weight"] = fuse_in(attn["value"]["kernel"])
        out[p + "self_attn.o_proj.weight"] = t(
            np.asarray(attn["out"]["kernel"]).reshape(-1, d_model).T
        )
        out[p + "post_attention_layernorm.weight"] = t(
            layer["mlp_norm"]["scale"])
        for name in ("gate_proj", "up_proj", "down_proj"):
            out[p + f"mlp.{name}.weight"] = t(
                np.asarray(layer[name]["kernel"]).T)
        i += 1
    return out


def bert_params_from_torch(
    state_dict: Mapping[str, Any], *, num_layers: int, num_heads: int
) -> dict:
    """HF ``BertForMaskedLM.state_dict()`` → params for models/bert.py.

    Architectural note: models/bert.py uses flax's tanh-approximate gelu
    (the original TF-BERT activation) — HF checkpoints configured with
    ``hidden_act='gelu'`` (exact erf) convert fine but diverge at the
    ~1e-3 level; ``gelu_new``/``gelu_pytorch_tanh`` checkpoints match
    tightly. Set ``ModelConfig.extra['ln_eps']`` to the checkpoint's
    ``layer_norm_eps`` (HF default 1e-12) when building the model. The
    unused pooler head (when present) is dropped — it does not feed MLM
    logits.
    """
    sd = _TrackingDict(state_dict)
    e = "bert.embeddings."
    embed = to_numpy(sd[e + "word_embeddings.weight"])  # (V, D)
    d_model = embed.shape[1]
    if d_model % num_heads:
        raise ValueError(f"d_model {d_model} % num_heads {num_heads} != 0")
    head_dim = d_model // num_heads

    def ln(prefix):
        return _ln_leaf(sd, prefix)

    def dense(prefix):
        return _dense_leaf(sd, prefix)

    params: dict = {
        "tok_embed": {"embedding": embed},
        "pos_embed": {"embedding": to_numpy(
            sd[e + "position_embeddings.weight"])},
        "type_embed": {"embedding": to_numpy(
            sd[e + "token_type_embeddings.weight"])},
        "ln_embed": ln(e + "LayerNorm"),
    }
    for i in range(num_layers):
        p = f"bert.encoder.layer.{i}."

        def heads_in(prefix):
            return _heads_in_leaf(sd, prefix, num_heads, head_dim)

        params[f"layer{i}"] = {
            "attn": {
                "query": heads_in(p + "attention.self.query"),
                "key": heads_in(p + "attention.self.key"),
                "value": heads_in(p + "attention.self.value"),
                "out": {
                    "kernel": _heads_out_kernel(
                        sd[p + "attention.output.dense.weight"],
                        num_heads, head_dim),
                    "bias": to_numpy(
                        sd[p + "attention.output.dense.bias"]),
                },
            },
            "ln1": ln(p + "attention.output.LayerNorm"),
            "mlp_in": dense(p + "intermediate.dense"),
            "mlp_out": dense(p + "output.dense"),
            "ln2": ln(p + "output.LayerNorm"),
        }
    params["mlm_dense"] = dense("cls.predictions.transform.dense")
    params["mlm_ln"] = ln("cls.predictions.transform.LayerNorm")
    decoder = {"kernel": to_numpy(sd["cls.predictions.decoder.weight"]).T,
               "bias": to_numpy(sd["cls.predictions.bias"])}
    sd.get("cls.predictions.decoder.bias")  # alias of cls.predictions.bias
    params["mlm_decoder"] = decoder
    sd.check_consumed(ignorable=("position_ids", "pooler"))
    return params


def gpt2_params_from_torch(
    state_dict: Mapping[str, Any], *, num_layers: int, num_heads: int
) -> dict:
    """HF ``GPT2LMHeadModel.state_dict()`` → params for
    models/transformer_lm.py (the same architecture: pre-LN blocks,
    learned positions, tanh-approximate gelu, biased attention, tied LM
    head).

    HF GPT-2 uses ``Conv1D`` layers whose weights are stored ``(in,
    out)`` — the flax kernel layout already, so unlike ``nn.Linear``
    nothing transposes. The fused ``c_attn`` (D, 3D) splits into q/k/v;
    the causal-mask ``attn.bias`` buffers are non-learned and ignored.
    Set ``ModelConfig.extra['ln_eps']`` to the checkpoint's
    ``layer_norm_epsilon`` (1e-5 for stock GPT-2) when building the
    model.
    """
    sd = _TrackingDict(state_dict)
    embed = to_numpy(sd["transformer.wte.weight"])  # (V, D)
    d_model = embed.shape[1]
    if d_model % num_heads:
        raise ValueError(f"d_model {d_model} % num_heads {num_heads} != 0")
    head_dim = d_model // num_heads

    def ln(prefix: str) -> dict:
        return {"scale": to_numpy(sd[prefix + ".weight"]),
                "bias": to_numpy(sd[prefix + ".bias"])}

    def conv1d(prefix: str) -> dict:  # (in, out) — flax layout already
        return {"kernel": to_numpy(sd[prefix + ".weight"]),
                "bias": to_numpy(sd[prefix + ".bias"])}

    params: dict = {
        "tok_embed": {"embedding": embed},
        "pos_embed": {"embedding": to_numpy(
            sd["transformer.wpe.weight"])},
    }
    for i in range(num_layers):
        p = f"transformer.h.{i}."
        ca_w = to_numpy(sd[p + "attn.c_attn.weight"])  # (D, 3D)
        ca_b = to_numpy(sd[p + "attn.c_attn.bias"])    # (3D,)
        qkv_w = np.split(ca_w, 3, axis=1)
        qkv_b = np.split(ca_b, 3)
        heads = {
            name: {
                "kernel": w.reshape(d_model, num_heads, head_dim),
                "bias": b.reshape(num_heads, head_dim),
            }
            for name, w, b in zip(("query", "key", "value"), qkv_w, qkv_b)
        }
        proj = conv1d(p + "attn.c_proj")
        heads["out"] = {
            "kernel": proj["kernel"].reshape(num_heads, head_dim, d_model),
            "bias": proj["bias"],
        }
        params[f"block{i}"] = {
            "ln1": ln(p + "ln_1"),
            "attn": heads,
            "ln2": ln(p + "ln_2"),
            "mlp_in": conv1d(p + "mlp.c_fc"),
            "mlp_out": conv1d(p + "mlp.c_proj"),
        }
    params["ln_f"] = ln("transformer.ln_f")
    lm_head = sd.get("lm_head.weight")  # tied to wte in stock GPT-2
    params["lm_head"] = {
        "kernel": (to_numpy(lm_head) if lm_head is not None else embed).T
    }
    sd.check_consumed(ignorable=(".attn.bias", ".attn.masked_bias"))
    return params


def mlp_params_from_torch(state_dict: Mapping[str, Any]) -> dict:
    """torch ``nn.Sequential`` of Linears (the reference's
    ``Net(nn.Module)``, SURVEY.md §2a) → params for models/mlp.py.

    Linear layers are taken in state-dict order (torch preserves
    registration order), mapping the j-th Linear to ``Dense_j``. Only
    2-D weights qualify as Linear kernels; any other weight tensor
    (BatchNorm/LayerNorm scales are 1-D) means the module isn't the
    plain Linear stack models/mlp.py implements — raise rather than
    load garbage under shifted layer indices.
    """
    weights = [k for k in state_dict if k.endswith(".weight")]
    non_linear = [k for k in weights
                  if to_numpy(state_dict[k]).ndim != 2]
    if non_linear:
        raise ValueError(
            f"non-Linear weight tensors {non_linear} — models/mlp.py is a "
            "plain Linear stack; convert norm-bearing nets via a "
            "model-specific mapping instead"
        )
    params: dict = {}
    for j, wk in enumerate(weights):
        leaf = {"kernel": linear_kernel(state_dict[wk])}
        bk = wk[: -len(".weight")] + ".bias"
        if bk in state_dict:
            leaf["bias"] = to_numpy(state_dict[bk])
        params[f"Dense_{j}"] = leaf
    return params


def _conv_kernel(weight) -> np.ndarray:
    """torch Conv2d weight (O, I, kh, kw) → flax kernel (kh, kw, I, O)."""
    return to_numpy(weight).transpose(2, 3, 1, 0)


def _bn_from_torch(tracked, prefix: str) -> tuple[dict, dict]:
    """torch BatchNorm2d → (flax params {scale, bias},
    batch_stats {mean, var})."""
    params = {"scale": to_numpy(tracked[prefix + ".weight"]),
              "bias": to_numpy(tracked[prefix + ".bias"])}
    stats = {"mean": to_numpy(tracked[prefix + ".running_mean"]),
             "var": to_numpy(tracked[prefix + ".running_var"])}
    return params, stats


def resnet50_params_from_torch(
    state_dict: Mapping[str, Any],
    *,
    stage_sizes: tuple[int, ...] = (3, 4, 6, 3),
    stem: str = "conv7",
) -> tuple[dict, dict]:
    """torchvision ``resnet50().state_dict()`` → (params, batch_stats)
    for models/resnet.py — the reference's config-2 model family, so a
    migrant's ImageNet checkpoint drops straight in.

    Key layout bridged (torchvision side): ``conv1``/``bn1`` stem,
    ``layer{1..4}.{b}.{conv1,bn1,conv2,bn2,conv3,bn3}`` bottlenecks
    with ``downsample.{0,1}`` projections on each stage's first block,
    ``fc`` head. Conv kernels transpose (O, I, kh, kw) → (kh, kw, I,
    O); BatchNorm running stats land in the ``batch_stats`` collection
    (our model's geometry matches torch's symmetric paddings, so
    converted weights are logit-equivalent in eval mode).
    """
    tracked = _TrackingDict(state_dict)
    k7 = _conv_kernel(tracked["conv1.weight"])
    if stem == "s2d":
        # the space-to-depth stem (models/resnet.py): the 7x7 kernel
        # rewrites EXACTLY to the 4x4/12-channel layout, so torchvision
        # checkpoints drop into s2d models logit-equivalently too
        from pytorch_distributed_nn_tpu.models.resnet import (
            conv7_to_s2d_kernel,
        )

        params: dict = {"conv_init_s2d": {
            "kernel": np.asarray(conv7_to_s2d_kernel(k7))}}
    elif stem == "conv7":
        params = {"conv_init": {"kernel": k7}}
    else:
        raise ValueError(f"unknown stem {stem!r}")
    stats: dict = {}
    params["bn_init"], stats["bn_init"] = _bn_from_torch(tracked, "bn1")

    for stage, n_blocks in enumerate(stage_sizes):
        for block in range(n_blocks):
            src = f"layer{stage + 1}.{block}"
            dst = f"stage{stage}_block{block}"
            p: dict = {}
            s: dict = {}
            for j in (1, 2, 3):
                p[f"conv{j}"] = {"kernel": _conv_kernel(
                    tracked[f"{src}.conv{j}.weight"])}
                p[f"bn{j}"], s[f"bn{j}"] = _bn_from_torch(
                    tracked, f"{src}.bn{j}")
            if f"{src}.downsample.0.weight" in state_dict:
                p["conv_proj"] = {"kernel": _conv_kernel(
                    tracked[f"{src}.downsample.0.weight"])}
                p["bn_proj"], s["bn_proj"] = _bn_from_torch(
                    tracked, f"{src}.downsample.1")
            params[dst] = p
            stats[dst] = s

    params["head"] = {
        "kernel": to_numpy(tracked["fc.weight"]).T,
        "bias": to_numpy(tracked["fc.bias"]),
    }
    tracked.check_consumed(ignorable=("num_batches_tracked",))
    return params, {"batch_stats": stats}


def resnet50_params_to_torch(params: Mapping[str, Any],
                             model_state: Mapping[str, Any],
                             *,
                             stage_sizes: tuple[int, ...] = (3, 4, 6, 3),
                             ) -> dict:
    """Inverse of :func:`resnet50_params_from_torch` (torchvision key
    layout, torch tensors). ``model_state`` is the TrainState field
    that function returns — the {'batch_stats': ...} wrapper, exactly
    what ``state.model_state`` holds."""
    import torch

    sd: dict = {}

    def put_conv(key, kernel):
        sd[key + ".weight"] = torch.from_numpy(
            np.asarray(kernel, np.float32).transpose(3, 2, 0, 1).copy())

    def put_bn(key, p, s):
        sd[key + ".weight"] = torch.from_numpy(
            np.asarray(p["scale"], np.float32).copy())
        sd[key + ".bias"] = torch.from_numpy(
            np.asarray(p["bias"], np.float32).copy())
        sd[key + ".running_mean"] = torch.from_numpy(
            np.asarray(s["mean"], np.float32).copy())
        sd[key + ".running_var"] = torch.from_numpy(
            np.asarray(s["var"], np.float32).copy())
        sd[key + ".num_batches_tracked"] = torch.zeros((), dtype=torch.int64)

    stats = model_state["batch_stats"]
    if "conv_init_s2d" in params:  # s2d stem: exact inverse rewrite
        from pytorch_distributed_nn_tpu.models.resnet import (
            s2d_kernel_to_conv7,
        )

        put_conv("conv1", np.asarray(
            s2d_kernel_to_conv7(params["conv_init_s2d"]["kernel"])))
    else:
        put_conv("conv1", params["conv_init"]["kernel"])
    put_bn("bn1", params["bn_init"], stats["bn_init"])
    for stage, n_blocks in enumerate(stage_sizes):
        for block in range(n_blocks):
            src = f"stage{stage}_block{block}"
            dst = f"layer{stage + 1}.{block}"
            for j in (1, 2, 3):
                put_conv(f"{dst}.conv{j}",
                         params[src][f"conv{j}"]["kernel"])
                put_bn(f"{dst}.bn{j}", params[src][f"bn{j}"],
                       stats[src][f"bn{j}"])
            if "conv_proj" in params[src]:
                put_conv(f"{dst}.downsample.0",
                         params[src]["conv_proj"]["kernel"])
                put_bn(f"{dst}.downsample.1", params[src]["bn_proj"],
                       stats[src]["bn_proj"])
    sd["fc.weight"] = torch.from_numpy(
        np.asarray(params["head"]["kernel"], np.float32).T.copy())
    sd["fc.bias"] = torch.from_numpy(
        np.asarray(params["head"]["bias"], np.float32).copy())
    return sd


def lenet_params_from_torch(state_dict: Mapping[str, Any]) -> dict:
    """torch LeNet-style nets (the reference's classic small CNN:
    conv(6,5,pad 2) -> pool -> conv(16,5) -> pool -> fc 120/84/classes)
    → params for models/lenet.py.

    Layers are taken in registration order like
    :func:`mlp_params_from_torch`: 4-D weights become ``Conv_i``, 2-D
    weights ``Dense_i``. The first Linear after the flatten needs its
    input rows PERMUTED: torch flattens NCHW (channel-major,
    ``c*H*W + h*W + w``) while our NHWC model flattens channel-minor
    (``h*W*C + w*C + c``) — same features, different order.
    """
    convs = [k for k in state_dict
             if k.endswith(".weight")
             and to_numpy(state_dict[k]).ndim == 4]
    fcs = [k for k in state_dict
           if k.endswith(".weight")
           and to_numpy(state_dict[k]).ndim == 2]
    if not convs or not fcs:
        raise ValueError(
            "lenet mapping needs Conv2d and Linear weights; got "
            f"convs={convs}, linears={fcs}"
        )
    # fail loudly on anything this layout does not map (BatchNorm
    # scales/stats, etc.) — a silently-dropped tensor means silently
    # wrong logits
    mapped = set(convs) | set(fcs)
    mapped |= {k[: -len(".weight")] + ".bias" for k in mapped}
    unmapped = [k for k in state_dict if k not in mapped]
    if unmapped:
        raise ValueError(
            "tensors the lenet layout does not map (norm-bearing or "
            f"non-standard variant?): {sorted(unmapped)[:8]}"
        )
    params: dict = {}
    for i, key in enumerate(convs):
        leaf = {"kernel": _conv_kernel(state_dict[key])}
        bk = key[: -len(".weight")] + ".bias"
        if bk in state_dict:
            leaf["bias"] = to_numpy(state_dict[bk])
        params[f"Conv_{i}"] = leaf

    channels = to_numpy(state_dict[convs[-1]]).shape[0]  # last conv out
    for j, key in enumerate(fcs):
        w = to_numpy(state_dict[key])  # (out, in)
        if j == 0:
            n_in = w.shape[1]
            if n_in % channels:
                raise ValueError(
                    f"first Linear in_features {n_in} not divisible by "
                    f"final conv channels {channels}"
                )
            hw = n_in // channels
            side = int(round(hw ** 0.5))
            if side * side != hw:
                raise ValueError(
                    f"non-square feature map ({hw} spatial elements) — "
                    "pass through a model-specific mapping"
                )
            # torch index c*H*W + h*W + w  ->  flax h*W*C + w*C + c.
            # ASSUMES a square final feature map (models/lenet.py
            # geometry); a rectangular map with square area would
            # permute with the wrong (H, W) and cannot be detected
            # from the state_dict alone.
            perm = (np.arange(n_in)
                    .reshape(channels, side, side)  # (c, h, w)
                    .transpose(1, 2, 0)  # (h, w, c)
                    .reshape(-1))
            w = w[:, perm]
        leaf = {"kernel": linear_kernel(w)}
        bk = key[: -len(".weight")] + ".bias"
        if bk in state_dict:
            leaf["bias"] = to_numpy(state_dict[bk])
        params[f"Dense_{j}"] = leaf
    return params


def vit_params_from_torch(
    state_dict: Mapping[str, Any], *, num_layers: int, num_heads: int
) -> dict:
    """HF ``ViTForImageClassification.state_dict()`` → params for
    models/vit.py (both are pre-LN encoders with CLS token + learned
    positions, so the mapping is 1:1).

    Same activation note as BERT: models/vit.py uses flax's
    tanh-approximate gelu — ``hidden_act='gelu_pytorch_tanh'``
    checkpoints match tightly, plain ``'gelu'`` (erf) diverges at the
    ~1e-3 level. The unused pooler (when present) is dropped.
    """
    sd = _TrackingDict(state_dict)
    proj = to_numpy(sd["vit.embeddings.patch_embeddings.projection"
                       ".weight"])  # (D, C, p, p)
    d_model = proj.shape[0]
    if d_model % num_heads:
        raise ValueError(f"d_model {d_model} % num_heads {num_heads} != 0")
    head_dim = d_model // num_heads

    def ln(prefix):
        return _ln_leaf(sd, prefix)

    def dense(prefix):
        return _dense_leaf(sd, prefix)

    def heads_in(prefix):
        return _heads_in_leaf(sd, prefix, num_heads, head_dim)

    params: dict = {
        "patch_embed": {
            "kernel": _conv_kernel(proj),
            "bias": to_numpy(sd["vit.embeddings.patch_embeddings"
                                ".projection.bias"]),
        },
        "cls": to_numpy(sd["vit.embeddings.cls_token"]),
        "pos_embed": to_numpy(sd["vit.embeddings.position_embeddings"]),
        "ln_f": ln("vit.layernorm"),
        "head": dense("classifier"),
    }
    for i in range(num_layers):
        p = f"vit.encoder.layer.{i}."
        params[f"layer{i}"] = {
            "attn": {
                "query": heads_in(p + "attention.attention.query"),
                "key": heads_in(p + "attention.attention.key"),
                "value": heads_in(p + "attention.attention.value"),
                "out": {
                    "kernel": _heads_out_kernel(
                        sd[p + "attention.output.dense.weight"],
                        num_heads, head_dim),
                    "bias": to_numpy(
                        sd[p + "attention.output.dense.bias"]),
                },
            },
            "ln1": ln(p + "layernorm_before"),
            "ln2": ln(p + "layernorm_after"),
            "mlp_in": dense(p + "intermediate.dense"),
            "mlp_out": dense(p + "output.dense"),
        }
    sd.check_consumed(ignorable=("pooler",))
    return params


def _tt(x):
    # copy=True: jax.device_get hands back non-writable zero-copy host
    # buffers, and torch.from_numpy would alias them (same hazard the
    # llama exporter's t() documents). Dtype is preserved.
    import torch

    return torch.from_numpy(np.array(x, copy=True))


def _ln_to_torch(sd: dict, prefix: str, leaf: Mapping[str, Any]) -> None:
    sd[prefix + ".weight"] = _tt(leaf["scale"])
    sd[prefix + ".bias"] = _tt(leaf["bias"])


def _dense_to_torch(sd: dict, prefix: str,
                    leaf: Mapping[str, Any]) -> None:
    sd[prefix + ".weight"] = _tt(np.asarray(leaf["kernel"]).T)
    if "bias" in leaf:
        sd[prefix + ".bias"] = _tt(leaf["bias"])


def _heads_in_to_torch(sd: dict, prefix: str,
                       leaf: Mapping[str, Any]) -> None:
    k = np.asarray(leaf["kernel"])  # (D, H, Dh)
    d = k.shape[0]
    sd[prefix + ".weight"] = _tt(k.reshape(d, -1).T)
    sd[prefix + ".bias"] = _tt(np.asarray(leaf["bias"]).reshape(-1))


def _heads_out_to_torch(sd: dict, prefix: str,
                        leaf: Mapping[str, Any]) -> None:
    k = np.asarray(leaf["kernel"])  # (H, Dh, D)
    d = k.shape[-1]
    sd[prefix + ".weight"] = _tt(k.reshape(-1, d).T)
    sd[prefix + ".bias"] = _tt(leaf["bias"])


def _layer_count(params: Mapping[str, Any], stem: str) -> int:
    n = len([k for k in params if k.startswith(stem)])
    if not n:
        raise ValueError(f"no {stem}* entries in params")
    return n


def _maybe_untied_head(sd: dict, key: str, head: np.ndarray,
                       embed: np.ndarray, tie_note: str) -> None:
    """Stock HF LM heads are TIED to the embedding table (shared
    storage), so a state_dict carrying both would let whichever loads
    last clobber the other. When the trained head still equals the
    embeddings, omit the head key — the tied model regenerates it.
    When training has untied them, keep it and warn: such a checkpoint
    must load into an untied config (tie_word_embeddings=False)."""
    if head.shape == embed.shape and np.array_equal(head, embed):
        return
    import warnings

    warnings.warn(
        f"exported head {key!r} differs from the embedding table; "
        f"{tie_note} by default, and loading this state_dict into a "
        "tied model would silently clobber the embeddings — use an "
        "untied config (tie_word_embeddings=False)", stacklevel=3)
    sd[key] = _tt(head)


def bert_params_to_torch(params: Mapping[str, Any]) -> dict:
    """Inverse of :func:`bert_params_from_torch` (HF ``BertForMaskedLM``
    key layout; the non-persistent ``position_ids`` buffer is omitted —
    load with ``strict=False`` on transformers versions that still
    register it)."""
    sd: dict = {}
    e = "bert.embeddings."
    sd[e + "word_embeddings.weight"] = _tt(
        params["tok_embed"]["embedding"])
    sd[e + "position_embeddings.weight"] = _tt(
        params["pos_embed"]["embedding"])
    sd[e + "token_type_embeddings.weight"] = _tt(
        params["type_embed"]["embedding"])
    _ln_to_torch(sd, e + "LayerNorm", params["ln_embed"])
    for i in range(_layer_count(params, "layer")):
        p = f"bert.encoder.layer.{i}."
        lp = params[f"layer{i}"]
        _heads_in_to_torch(sd, p + "attention.self.query",
                           lp["attn"]["query"])
        _heads_in_to_torch(sd, p + "attention.self.key",
                           lp["attn"]["key"])
        _heads_in_to_torch(sd, p + "attention.self.value",
                           lp["attn"]["value"])
        _heads_out_to_torch(sd, p + "attention.output.dense",
                            lp["attn"]["out"])
        _ln_to_torch(sd, p + "attention.output.LayerNorm", lp["ln1"])
        _dense_to_torch(sd, p + "intermediate.dense", lp["mlp_in"])
        _dense_to_torch(sd, p + "output.dense", lp["mlp_out"])
        _ln_to_torch(sd, p + "output.LayerNorm", lp["ln2"])
    _dense_to_torch(sd, "cls.predictions.transform.dense",
                    params["mlm_dense"])
    _ln_to_torch(sd, "cls.predictions.transform.LayerNorm",
                 params["mlm_ln"])
    _maybe_untied_head(
        sd, "cls.predictions.decoder.weight",
        np.asarray(params["mlm_decoder"]["kernel"]).T,
        np.asarray(params["tok_embed"]["embedding"]),
        "BertForMaskedLM ties cls.predictions.decoder to the word "
        "embeddings")
    sd["cls.predictions.bias"] = _tt(params["mlm_decoder"]["bias"])
    sd["cls.predictions.decoder.bias"] = sd["cls.predictions.bias"]
    return sd


def gpt2_params_to_torch(params: Mapping[str, Any]) -> dict:
    """Inverse of :func:`gpt2_params_from_torch` (HF ``GPT2LMHeadModel``
    layout: Conv1D weights stay (in, out), q/k/v re-fuse into
    ``c_attn``). ``lm_head.weight`` appears ONLY when training untied
    it from ``wte`` (see :func:`_maybe_untied_head`); stock tied
    checkpoints regenerate the head from the embeddings on load."""
    sd: dict = {}
    sd["transformer.wte.weight"] = _tt(params["tok_embed"]["embedding"])
    sd["transformer.wpe.weight"] = _tt(params["pos_embed"]["embedding"])

    def conv1d(prefix, leaf):
        sd[prefix + ".weight"] = _tt(leaf["kernel"])
        sd[prefix + ".bias"] = _tt(leaf["bias"])

    for i in range(_layer_count(params, "block")):
        p = f"transformer.h.{i}."
        bp = params[f"block{i}"]
        _ln_to_torch(sd, p + "ln_1", bp["ln1"])
        _ln_to_torch(sd, p + "ln_2", bp["ln2"])
        qkv = bp["attn"]
        d = np.asarray(qkv["query"]["kernel"]).shape[0]
        sd[p + "attn.c_attn.weight"] = _tt(np.concatenate(
            [np.asarray(qkv[n]["kernel"]).reshape(d, -1)
             for n in ("query", "key", "value")], axis=1))
        sd[p + "attn.c_attn.bias"] = _tt(np.concatenate(
            [np.asarray(qkv[n]["bias"]).reshape(-1)
             for n in ("query", "key", "value")]))
        out = qkv["out"]
        sd[p + "attn.c_proj.weight"] = _tt(
            np.asarray(out["kernel"]).reshape(-1, d))
        sd[p + "attn.c_proj.bias"] = _tt(out["bias"])
        conv1d(p + "mlp.c_fc", bp["mlp_in"])
        conv1d(p + "mlp.c_proj", bp["mlp_out"])
    _ln_to_torch(sd, "transformer.ln_f", params["ln_f"])
    _maybe_untied_head(
        sd, "lm_head.weight",
        np.asarray(params["lm_head"]["kernel"]).T,
        np.asarray(params["tok_embed"]["embedding"]),
        "GPT2LMHeadModel ties lm_head to transformer.wte")
    return sd


def vit_params_to_torch(params: Mapping[str, Any]) -> dict:
    """Inverse of :func:`vit_params_from_torch`
    (HF ``ViTForImageClassification`` layout)."""
    sd: dict = {}
    sd["vit.embeddings.cls_token"] = _tt(params["cls"])
    sd["vit.embeddings.position_embeddings"] = _tt(params["pos_embed"])
    sd["vit.embeddings.patch_embeddings.projection.weight"] = _tt(
        np.asarray(params["patch_embed"]["kernel"])
        .transpose(3, 2, 0, 1))
    sd["vit.embeddings.patch_embeddings.projection.bias"] = _tt(
        params["patch_embed"]["bias"])
    for i in range(_layer_count(params, "layer")):
        p = f"vit.encoder.layer.{i}."
        lp = params[f"layer{i}"]
        _heads_in_to_torch(sd, p + "attention.attention.query",
                           lp["attn"]["query"])
        _heads_in_to_torch(sd, p + "attention.attention.key",
                           lp["attn"]["key"])
        _heads_in_to_torch(sd, p + "attention.attention.value",
                           lp["attn"]["value"])
        _heads_out_to_torch(sd, p + "attention.output.dense",
                            lp["attn"]["out"])
        _ln_to_torch(sd, p + "layernorm_before", lp["ln1"])
        _ln_to_torch(sd, p + "layernorm_after", lp["ln2"])
        _dense_to_torch(sd, p + "intermediate.dense", lp["mlp_in"])
        _dense_to_torch(sd, p + "output.dense", lp["mlp_out"])
    _ln_to_torch(sd, "vit.layernorm", params["ln_f"])
    _dense_to_torch(sd, "classifier", params["head"])
    return sd
