"""Host→device loader with per-process sharding and background prefetch.

Replaces the reference's ``DataLoader`` + ``DistributedSampler`` pair
(SURVEY.md §2a): each host process materialises only its slice of the
global batch, then the slices are assembled into one global ``jax.Array``
sharded over the mesh's data axes. A background thread keeps ``prefetch``
batches in flight so host generation overlaps device compute (the TPU
analogue of torch's pinned-memory worker pool).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from pytorch_distributed_nn_tpu.data.datasets import SyntheticDataset
from pytorch_distributed_nn_tpu.runtime.mesh import AXIS_SEQ, batch_pspec


def array_pspec(mesh: Mesh, ndim: int, seq_len: int | None):
    """Batch layout for one array: rows over data×fsdp always; a
    (B, T) token array additionally shards T over ``seq`` when the mesh
    has sequence parallelism (model-level ring attention expects its
    activations sequence-sharded from the start — see parallel/api.py
    validation)."""
    seq = mesh.shape.get(AXIS_SEQ, 1)
    if seq > 1 and ndim == 2 and seq_len and seq_len % seq == 0:
        return batch_pspec(AXIS_SEQ)
    return batch_pspec()


class DataLoader:
    def __init__(
        self,
        dataset: SyntheticDataset,
        mesh: Mesh,
        *,
        start_step: int = 0,
        prefetch: int = 2,
    ) -> None:
        self.dataset = dataset
        self.mesh = mesh
        self.start_step = start_step
        self.prefetch = prefetch
        gbs = dataset.batch_size
        n_proc = jax.process_count()
        if gbs % n_proc:
            raise ValueError(
                f"global batch {gbs} not divisible by {n_proc} processes"
            )
        from pytorch_distributed_nn_tpu.runtime.mesh import data_axis_size

        dp = data_axis_size(mesh)
        if gbs % dp:
            raise ValueError(
                f"global batch {gbs} not divisible by data degree {dp}"
            )
        if (mesh.shape.get(AXIS_SEQ, 1) > 1 and jax.process_count() > 1
                and self._seq_spans_processes(mesh)):
            # _host_slice hands each process its batch rows with the
            # FULL sequence dim; that is only the process's addressable
            # portion when every seq-axis device is process-local
            raise NotImplementedError(
                "sequence sharding across processes is not supported: "
                "keep the seq mesh axis within one host (it wants ICI "
                "anyway) and put data/pipe across hosts"
            )

    @staticmethod
    def _seq_spans_processes(mesh: Mesh) -> bool:
        devs = np.asarray(mesh.devices)
        seq_axis = list(mesh.axis_names).index(AXIS_SEQ)
        moved = np.moveaxis(devs, seq_axis, 0)
        for line in moved.reshape(moved.shape[0], -1).T:
            if len({d.process_index for d in line}) > 1:
                return True
        return False

    def _host_slice(self, arr: np.ndarray) -> np.ndarray:
        """The rows of the global batch this process owns (contiguous
        block layout, matching NamedSharding's row-major split)."""
        n = jax.process_count()
        per = arr.shape[0] // n
        i = jax.process_index()
        return arr[i * per:(i + 1) * per]

    def _to_global(self, arr: np.ndarray) -> jax.Array:
        sharding = NamedSharding(
            self.mesh,
            array_pspec(self.mesh, arr.ndim,
                        arr.shape[1] if arr.ndim >= 2 else None),
        )
        if jax.process_count() == 1:
            return jax.device_put(arr, sharding)
        return jax.make_array_from_process_local_data(
            sharding, self._host_slice(arr)
        )

    def batch_at(self, step: int) -> tuple[jax.Array, ...]:
        """Deterministic global batch for one step (no prefetch)."""
        return tuple(self._to_global(a) for a in self.dataset.batch(step))

    def __iter__(self) -> Iterator[tuple[jax.Array, ...]]:
        if self.prefetch <= 0:
            step = self.start_step
            while True:
                yield self.batch_at(step)
                step += 1
            return

        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def producer() -> None:
            step = self.start_step
            while not stop.is_set():
                try:
                    batch = self.batch_at(step)
                except Exception as e:  # surface errors to the consumer
                    q.put(e)
                    return
                q.put(batch)
                step += 1

        thread = threading.Thread(target=producer, daemon=True)
        thread.start()
        try:
            while True:
                item = q.get()
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            stop.set()
            # unblock a producer stuck on a full queue
            while not q.empty():
                q.get_nowait()
