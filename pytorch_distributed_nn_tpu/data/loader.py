"""Host→device loader with per-process sharding and background prefetch.

Replaces the reference's ``DataLoader`` + ``DistributedSampler`` pair
(SURVEY.md §2a): every host process generates the same seed-deterministic
GLOBAL batch, and each feeds exactly the shards its devices own into one
global ``jax.Array`` (``make_array_from_callback``) — correct under any
mesh, including model-parallel layouts where the batch replicates across
processes. That determinism is the correctness precondition: a
per-process non-deterministic dataset would silently mis-assemble. A
background thread keeps ``prefetch`` batches in flight so host
generation overlaps device compute (the TPU analogue of torch's
pinned-memory worker pool).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from pytorch_distributed_nn_tpu import obs
from pytorch_distributed_nn_tpu.obs import flight
from pytorch_distributed_nn_tpu.data.datasets import SyntheticDataset
from pytorch_distributed_nn_tpu.runtime.mesh import AXIS_SEQ, batch_pspec


def array_pspec(mesh: Mesh, ndim: int, seq_len: int | None):
    """Batch layout for one array: rows over data×fsdp always; a
    (B, T) token array additionally shards T over ``seq`` when the mesh
    has sequence parallelism (model-level ring attention expects its
    activations sequence-sharded from the start — see parallel/api.py
    validation)."""
    seq = mesh.shape.get(AXIS_SEQ, 1)
    if seq > 1 and ndim == 2 and seq_len and seq_len % seq == 0:
        return batch_pspec(AXIS_SEQ)
    return batch_pspec()


class DataLoader:
    def __init__(
        self,
        dataset: SyntheticDataset,
        mesh: Mesh,
        *,
        start_step: int = 0,
        prefetch: int = 2,
    ) -> None:
        self.dataset = dataset
        self.mesh = mesh
        self.start_step = start_step
        self.prefetch = prefetch
        gbs = dataset.batch_size
        from pytorch_distributed_nn_tpu.runtime.mesh import data_axis_size

        dp = data_axis_size(mesh)
        if gbs % dp:
            raise ValueError(
                f"global batch {gbs} not divisible by data degree {dp}"
            )
        if (mesh.shape.get(AXIS_SEQ, 1) > 1 and jax.process_count() > 1
                and self._seq_spans_processes(mesh)):
            # the callback assembly (_assemble) could feed seq-sharded
            # rows across processes, but the ring-attention compute
            # path is untested across hosts and the seq axis wants ICI
            raise NotImplementedError(
                "sequence sharding across processes is not supported: "
                "keep the seq mesh axis within one host (it wants ICI "
                "anyway) and put data/pipe across hosts"
            )

    @staticmethod
    def _seq_spans_processes(mesh: Mesh) -> bool:
        devs = np.asarray(mesh.devices)
        seq_axis = list(mesh.axis_names).index(AXIS_SEQ)
        moved = np.moveaxis(devs, seq_axis, 0)
        for line in moved.reshape(moved.shape[0], -1).T:
            if len({d.process_index for d in line}) > 1:
                return True
        return False

    def _assemble(self, arr: np.ndarray, sharding) -> jax.Array:
        """Global jax.Array from the host-side global batch. The
        dataset's batches are seed-deterministic and identical on every
        process, so each process feeds exactly the shards its devices
        own via ``make_array_from_callback`` — correct for ANY
        sharding, including model-parallel meshes where the batch is
        REPLICATED across processes (r4: the 2-process pipeline gang
        test caught the old rows-split-by-process-index assembly
        feeding half a replicated batch)."""
        if jax.process_count() == 1:
            return jax.device_put(arr, sharding)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    def _to_global(self, arr: np.ndarray) -> jax.Array:
        sharding = NamedSharding(
            self.mesh,
            array_pspec(self.mesh, arr.ndim,
                        arr.shape[1] if arr.ndim >= 2 else None),
        )
        return self._assemble(arr, sharding)

    def batch_at(self, step: int) -> tuple[jax.Array, ...]:
        """Deterministic global batch for one step (no prefetch)."""
        # span covers host generation + shard assembly/transfer; when
        # prefetch is on it runs on the producer thread, so the trace
        # shows host data work overlapping device compute
        with obs.span("data/host_batch", step=step):
            arrs = self.dataset.batch(step)
            out = tuple(self._to_global(a) for a in arrs)
        # loader hand-off in the flight ring: runs on the prefetch
        # producer thread when prefetch is on
        flight.record("data", "host_batch", step=step,
                      nbytes=sum(int(a.nbytes) for a in arrs))
        obs.get_registry().counter(
            "data_batches_total", "host batches assembled").inc()
        return out

    def stacked_batch_at(self, step: int, k: int) -> tuple[jax.Array, ...]:
        """Batches for steps [step, step+k) stacked on a leading pool
        axis — the input layout of the device-side multistep loop
        (train/multistep.py): (k, B, ...) with the pool axis unsharded
        and the batch rows sharded exactly as :meth:`batch_at`."""
        with obs.span("data/host_batch_stacked", step=step, k=k):
            per_step = [self.dataset.batch(step + i) for i in range(k)]
            out = []
            for j in range(len(per_step[0])):
                arr = np.stack([b[j] for b in per_step])
                inner = array_pspec(
                    self.mesh, arr.ndim - 1,
                    arr.shape[2] if arr.ndim >= 3 else None)
                sharding = NamedSharding(self.mesh,
                                         PartitionSpec(None, *inner))
                out.append(self._assemble(arr, sharding))
        flight.record("data", "host_batch_stacked", step=step,
                      note=f"k={k}",
                      nbytes=sum(int(b[j].nbytes) for b in per_step
                                 for j in range(len(b))))
        obs.get_registry().counter(
            "data_batches_total", "host batches assembled").inc(k)
        return tuple(out)

    def _prefetched(self, make_items) -> Iterator:
        """Drive ``make_items`` (a generator of batches) through a
        background producer thread with a ``prefetch``-deep queue, so
        host generation + transfer overlaps device compute."""
        if self.prefetch <= 0:
            yield from make_items()
            return

        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        # unique end-of-stream marker: an Exception subclass (the old
        # StopIteration() sentinel) would swallow StopIteration-derived
        # errors escaping dataset code as a clean end of stream
        end_of_stream = object()

        def producer() -> None:
            try:
                for batch in make_items():
                    if stop.is_set():
                        return
                    q.put(batch)
            except Exception as e:  # surface errors to the consumer
                q.put(e)
                return
            q.put(end_of_stream)

        thread = threading.Thread(target=producer, daemon=True)
        thread.start()
        depth = obs.get_registry().gauge(
            "data_queue_depth", "prefetched batches waiting")
        try:
            while True:
                # the q.get wait IS the host data-wait the goodput
                # breakdown's "data" phase measures from the trainer;
                # the span makes it visible in traces independently
                with obs.span("data/queue_wait", cat="data"):
                    item = q.get()
                depth.set(q.qsize())
                if item is end_of_stream:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            stop.set()
            # unblock a producer stuck on a full queue
            while not q.empty():
                q.get_nowait()
            # join so the producer finishes its in-flight batch BEFORE
            # interpreter teardown: a daemon thread aborted mid-XLA-call
            # at exit dies with "terminate called ... FATAL: exception
            # not rethrown" (rare SIGABRT seen under full-suite load)
            thread.join(timeout=10.0)

    def __iter__(self) -> Iterator[tuple[jax.Array, ...]]:
        def gen():
            step = self.start_step
            while True:
                yield self.batch_at(step)
                step += 1

        yield from self._prefetched(gen)

    def iter_stacked(self, sizes: list[int],
                     *, start_step: int | None = None) -> Iterator:
        """Prefetching iterator over STACKED windows: yields
        ``stacked_batch_at(s, k)`` for consecutive windows of the given
        sizes — the input stream of the Trainer's device-side multistep
        loop, with the same background-thread overlap as ``__iter__``
        (without it the device would idle through host RNG + stack +
        transfer of k batches between fused dispatches)."""
        def gen():
            step = self.start_step if start_step is None else start_step
            for k in sizes:
                yield self.stacked_batch_at(step, k)
                step += k

        yield from self._prefetched(gen)
