"""On-disk readers for the standard dataset formats the reference
consumed via ``torchvision.datasets`` (SURVEY.md §2a Data-loading row):
MNIST idx files, CIFAR-10 binary batches, and class-per-directory image
folders. Zero-egress container: these read files the user already has —
nothing downloads.

All three feed :class:`~..data.datasets.ArraySampler`, so they inherit
the (seed, step)-deterministic epoch-shuffle sampling (torch
``DistributedSampler`` semantics) and the held-out eval contract; when
the on-disk layout carries a REAL test split (t10k-* files,
test_batch.bin, a val/ directory) it becomes the eval stream
automatically, which is strictly better than a carved holdout.

Pixel scaling matches ``torchvision.transforms.ToTensor``: uint8 -> f32
in [0, 1]. (Mean/std normalization is a model-side choice, as in the
reference's per-script transforms.)
"""

from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path

import numpy as np

from pytorch_distributed_nn_tpu.data.datasets import (
    ArraySampler,
    BatchSpec,
)

_IDX_DTYPES = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
               0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64}


def _parse_idx_header(f, path) -> tuple[np.dtype, tuple[int, ...]]:
    """Read the idx header from an open stream: [0, 0, dtype, ndim] then
    ndim big-endian uint32 dims. Leaves ``f`` positioned at the data."""
    zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
    if zero != 0 or dtype_code not in _IDX_DTYPES:
        raise ValueError(f"{path}: not an idx file (magic "
                         f"{zero:#06x}/{dtype_code:#04x})")
    dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
    return (np.dtype(_IDX_DTYPES[dtype_code]),
            tuple(int(d) for d in dims))


def _idx_opener(path):
    return gzip.open if str(path).endswith(".gz") else open


def read_idx_header(path: str | Path) -> tuple[np.dtype, tuple[int, ...]]:
    """Parse only the idx header: (dtype, dims). Reads a handful of
    bytes — cheap enough for shape probes (e.g. FLOPs counting) that
    must not load a full corpus."""
    with _idx_opener(path)(path, "rb") as f:
        return _parse_idx_header(f, path)


def read_idx(path: str | Path) -> np.ndarray:
    """Parse one idx(1|3)-ubyte file (optionally .gz) — the LeCun MNIST
    container (header per :func:`_parse_idx_header`, then the raw
    array)."""
    with _idx_opener(path)(path, "rb") as f:
        native_dtype, dims = _parse_idx_header(f, path)
        # idx stores multi-byte dtypes big-endian: the bytes must be
        # REINTERPRETED as '>' at frombuffer time (converting after a
        # native-endian read would keep the swapped values)
        dtype = native_dtype.newbyteorder(">")
        data = np.frombuffer(f.read(), dtype=dtype)
    expected = int(np.prod(dims))
    if data.size != expected:
        raise ValueError(
            f"{path}: header promises {dims} = {expected} values, file "
            f"holds {data.size}"
        )
    return data.astype(dtype.newbyteorder("=")).reshape(dims)


def _find_one(root: Path, stem: str) -> Path | None:
    for name in (stem, stem + ".gz"):
        p = root / name
        if p.exists():
            return p
    return None


class _Uint8Pixels(ArraySampler):
    """Corpus kept at native uint8 (4x less resident RAM than f32);
    the [0, 1] scaling happens per batch in _gather."""

    def _gather(self, idx: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        return self.x[idx].astype(np.float32) / 255.0, self.y[idx]


class MnistIdxDataset(_Uint8Pixels):
    """MNIST from the standard idx files. ``path`` is the directory
    holding ``train-images-idx3-ubyte[.gz]`` / ``train-labels-idx1-
    ubyte[.gz]``; when the ``t10k-*`` pair is present it becomes the
    held-out eval stream (the real test set)."""

    def __init__(self, path: str, seed: int, batch_size: int, *,
                 sample: str = "shuffle",
                 holdout_frac: float = 0.0) -> None:
        root = Path(path)
        imgs = _find_one(root, "train-images-idx3-ubyte")
        lbls = _find_one(root, "train-labels-idx1-ubyte")
        if imgs is None or lbls is None:
            raise ValueError(
                f"{root}: need train-images-idx3-ubyte[.gz] + "
                "train-labels-idx1-ubyte[.gz]"
            )
        x = read_idx(imgs)
        y = read_idx(lbls)
        t_imgs = _find_one(root, "t10k-images-idx3-ubyte")
        t_lbls = _find_one(root, "t10k-labels-idx1-ubyte")
        if (t_imgs is None) != (t_lbls is None):
            # a half-present test pair would silently degrade eval to
            # the in-sample stream — as loud as a missing train pair
            raise ValueError(
                f"{root}: t10k pair incomplete (found "
                f"{'images' if t_imgs else 'labels'} without its mate)"
            )
        n_eval = 0
        if t_imgs is not None and t_lbls is not None:
            x = np.concatenate([x, read_idx(t_imgs)])
            ty = read_idx(t_lbls)
            y = np.concatenate([y, ty])
            n_eval = len(ty)
            holdout_frac = 0.0  # the real test set wins
        super().__init__(x, y, seed, batch_size, sample=sample,
                         holdout_frac=holdout_frac, n_eval_tail=n_eval)
        self.spec = BatchSpec(tuple(x.shape[1:]), np.dtype(np.float32),
                              (), np.dtype(np.int32),
                              int(self.y.max()) + 1)


class Cifar10BinDataset(_Uint8Pixels):
    """CIFAR-10 from the python-site ``.bin`` batches: each record is
    1 label byte + 3072 CHW pixel bytes. ``path`` is the directory
    holding ``data_batch_*.bin`` (train) and optionally
    ``test_batch.bin`` (becomes the eval stream)."""

    RECORD = 1 + 3 * 32 * 32

    @classmethod
    def _read_bin(cls, path: Path) -> tuple[np.ndarray, np.ndarray]:
        raw = np.frombuffer(path.read_bytes(), np.uint8)
        if raw.size % cls.RECORD:
            raise ValueError(
                f"{path}: size {raw.size} is not a multiple of the "
                f"{cls.RECORD}-byte CIFAR record"
            )
        rec = raw.reshape(-1, cls.RECORD)
        y = rec[:, 0]
        # CHW records -> HWC uint8 (scaling to [0,1] happens per batch)
        x = np.ascontiguousarray(
            rec[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        )
        return x, y

    def __init__(self, path: str, seed: int, batch_size: int, *,
                 sample: str = "shuffle",
                 holdout_frac: float = 0.0) -> None:
        root = Path(path)
        train_files = sorted(root.glob("data_batch_*.bin"))
        if not train_files:
            raise ValueError(f"{root}: no data_batch_*.bin files")
        parts = [self._read_bin(p) for p in train_files]
        x = np.concatenate([p[0] for p in parts])
        y = np.concatenate([p[1] for p in parts])
        test = root / "test_batch.bin"
        n_eval = 0
        if test.exists():
            tx, ty = self._read_bin(test)
            x = np.concatenate([x, tx])
            y = np.concatenate([y, ty])
            n_eval = len(ty)
            holdout_frac = 0.0
        super().__init__(x, y, seed, batch_size, sample=sample,
                         holdout_frac=holdout_frac, n_eval_tail=n_eval)
        self.spec = BatchSpec((32, 32, 3), np.dtype(np.float32), (),
                              np.dtype(np.int32), int(self.y.max()) + 1)


class ImageFolderDataset(ArraySampler):
    """torchvision-``ImageFolder`` layout: ``root/<class>/<image>``,
    class index = sorted directory order. Images decode LAZILY per
    batch (PIL), resized with a center-crop to ``image_size`` — the
    ImageNet-scale path where the corpus cannot live in RAM; the
    loader's background prefetch overlaps decode with device compute.

    ``num_workers`` threads decode a batch's images concurrently
    (torch ``DataLoader(num_workers=N)`` semantics at the batch level:
    0 = decode inline, -1 = one per core capped at 16). Threads — not
    processes — because PIL/libjpeg releases the GIL for the decode and
    resize hot paths, so worker threads scale across cores without
    pickling batches between processes (VERDICT r2 Missing #5; the
    per-core decode rate is measured by ``bench.py --metric loader
    --workers-sweep`` and recorded in BASELINE.md).

    ``root/train`` + ``root/val`` (each in class layout) are honored as
    the split when present — val/ becomes the eval stream; otherwise
    ``holdout_frac`` applies over the files.
    """

    EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".ppm", ".webp")

    @classmethod
    def _scan(cls, root: Path) -> tuple[list[Path], list[int], list[str]]:
        classes = sorted(d.name for d in root.iterdir() if d.is_dir())
        if not classes:
            raise ValueError(f"{root}: no class directories")
        paths, labels = [], []
        for ci, cname in enumerate(classes):
            files = sorted(
                p for p in (root / cname).rglob("*")
                if p.suffix.lower() in cls.EXTS
            )
            paths.extend(files)
            labels.extend([ci] * len(files))
        if not paths:
            raise ValueError(f"{root}: no image files under the class "
                             "directories")
        return paths, labels, classes

    def __init__(self, path: str, seed: int, batch_size: int, *,
                 sample: str = "shuffle", holdout_frac: float = 0.0,
                 image_size: int = 224, num_workers: int = 0) -> None:
        root = Path(path)
        self.image_size = image_size
        if num_workers < 0:
            num_workers = min(os.cpu_count() or 1, 16)
        self.num_workers = num_workers
        # eager: _gather is called from both the DataLoader's prefetch
        # producer thread and the main thread's eval path — lazy
        # construction would race and orphan an executor
        self._pool = None
        if num_workers > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=num_workers,
                thread_name_prefix="img-decode",
            )
        n_eval = 0
        if (root / "train").is_dir():
            paths, labels, classes = self._scan(root / "train")
            if (root / "val").is_dir():
                vp, vl, vclasses = self._scan(root / "val")
                if vclasses != classes:
                    raise ValueError(
                        f"{root}: train/ and val/ class sets differ"
                    )
                paths, labels = paths + vp, labels + vl
                n_eval = len(vl)
                holdout_frac = 0.0
        else:
            paths, labels, classes = self._scan(root)
        super().__init__(np.array([str(p) for p in paths]),
                         np.array(labels), seed, batch_size,
                         sample=sample, holdout_frac=holdout_frac,
                         n_eval_tail=n_eval)
        self.classes = classes
        self.spec = BatchSpec((image_size, image_size, 3),
                              np.dtype(np.float32), (),
                              np.dtype(np.int32), len(classes))

    def _decode(self, path: str) -> np.ndarray:
        from PIL import Image

        s = self.image_size
        with Image.open(path) as im:
            im = im.convert("RGB")
            # torchvision eval transform: scale short side, center-crop
            w, h = im.size
            scale = s / min(w, h)
            im = im.resize((max(s, round(w * scale)),
                            max(s, round(h * scale))), Image.BILINEAR)
            w, h = im.size
            left, top = (w - s) // 2, (h - s) // 2
            im = im.crop((left, top, left + s, top + s))
            return np.asarray(im, np.float32) / 255.0

    def _gather(self, idx: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        paths = self.x[idx]
        if self._pool is not None:
            x = np.stack(list(self._pool.map(self._decode, paths)))
        else:
            x = np.stack([self._decode(p) for p in paths])
        return x, self.y[idx]

    def close(self) -> None:
        """Shut the decode pool down (idle threads otherwise persist
        for the process lifetime — e.g. the bench worker sweep builds
        one dataset per sweep point)."""
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def __del__(self):  # best-effort; close() is the explicit path
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
