"""Data pipeline: deterministic synthetic datasets, per-process sharding
(the ``DistributedSampler`` equivalent — SURVEY.md §2a Data-loading row),
and a prefetching host→device loader."""

from pytorch_distributed_nn_tpu.data.datasets import get_dataset
from pytorch_distributed_nn_tpu.data.loader import DataLoader

__all__ = ["get_dataset", "DataLoader"]
