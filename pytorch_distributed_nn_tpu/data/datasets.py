"""Synthetic datasets, deterministic by (seed, step).

The reference feeds ``torchvision.datasets`` MNIST/CIFAR/ImageNet through
a ``DistributedSampler`` (SURVEY.md §2a). This container is zero-egress,
so the framework ships procedurally generated stand-ins with the same
shapes/dtypes and *learnable* structure (class-conditional templates for
vision, an affine next-token process for LM) — loss curves genuinely
descend, which the golden-equivalence tests rely on.

Determinism contract: ``batch(step)`` depends only on (seed, step, global
batch size) — never on topology — so any device/process layout sees the
identical global batch and distributed training is bit-comparable to
single-device training (SURVEY.md §4 "Golden-equivalence").
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Evaluation batches are requested at step >= this offset (Trainer's
# held-out stream convention). Synthetic streams are infinite, so the
# offset range alone is genuinely unseen data; FILE datasets are finite
# and need holdout_frac > 0 to actually reserve rows/tokens — with
# holdout_frac == 0 their "eval" draws from the training examples
# (in-sample) and scripts/eval.py reports train-set performance.
EVAL_STEP_OFFSET = 1 << 30


@dataclasses.dataclass(frozen=True)
class BatchSpec:
    x_shape: tuple[int, ...]  # per-example
    x_dtype: np.dtype
    y_shape: tuple[int, ...]
    y_dtype: np.dtype
    num_classes: int


class SyntheticDataset:
    """Base: infinite stream of batches, indexed by step."""

    spec: BatchSpec

    def __init__(self, seed: int, batch_size: int) -> None:
        self.seed = seed
        self.batch_size = batch_size

    def _rng(self, step: int) -> np.random.Generator:
        return np.random.default_rng(
            np.random.SeedSequence([self.seed, step])
        )

    def batch(self, step: int) -> tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError


class ClassTemplateImages(SyntheticDataset):
    """Class-conditional template + noise images: y ~ uniform(classes),
    x = template[y] + noise drawn from a pre-generated N(0, noise) pool.
    Linearly separable enough that small nets learn it fast, hard
    enough that loss curves are informative.

    The noise POOL (finite, like any real dataset's finite noise) is
    what makes the host pipeline feed a chip: fresh per-pixel gaussians
    for a 224^2 batch cost ~0.25 s/batch of single-core numpy — an
    input-bound pipeline — while indexing the pool is a gather+add.
    Per-batch draws stay (seed, step)-keyed: pool row choice and class
    labels are deterministic, preserving the any-topology contract."""

    def __init__(self, seed: int, batch_size: int, *,
                 shape: tuple[int, ...], num_classes: int,
                 noise: float = 0.35, noise_pool: int = 256) -> None:
        super().__init__(seed, batch_size)
        self.noise = noise
        self.spec = BatchSpec(shape, np.dtype(np.float32), (),
                              np.dtype(np.int32), num_classes)
        tmpl_rng = np.random.default_rng(
            np.random.SeedSequence([seed, 0xC1A55])
        )
        self.templates = tmpl_rng.standard_normal(
            (num_classes, *shape), dtype=np.float32
        )
        pool = tmpl_rng.standard_normal(
            (max(noise_pool, 2), *shape), dtype=np.float32
        )
        pool *= noise
        self._pool = pool

    def batch(self, step: int) -> tuple[np.ndarray, np.ndarray]:
        rng = self._rng(step)
        y = rng.integers(0, self.spec.num_classes, size=self.batch_size,
                         dtype=np.int32)
        idx = rng.integers(0, len(self._pool), size=self.batch_size)
        x = self.templates[y] + self._pool[idx]
        return x, y


class SyntheticLM(SyntheticDataset):
    """Learnable token stream: tokens follow a noised affine recurrence
    t_{i+1} = (a·t_i + c) mod V, with a fraction of uniform-random tokens.
    Targets are inputs shifted by one (standard causal LM)."""

    def __init__(self, seed: int, batch_size: int, *, seq_len: int,
                 vocab_size: int, noise_frac: float = 0.1) -> None:
        super().__init__(seed, batch_size)
        self.seq_len = seq_len
        self.noise_frac = noise_frac
        self.spec = BatchSpec((seq_len,), np.dtype(np.int32), (seq_len,),
                              np.dtype(np.int32), vocab_size)
        self.a = 31337 % vocab_size or 1
        self.c = 7919 % vocab_size

    def batch(self, step: int) -> tuple[np.ndarray, np.ndarray]:
        rng = self._rng(step)
        V = self.spec.num_classes
        toks = np.empty((self.batch_size, self.seq_len + 1), dtype=np.int64)
        toks[:, 0] = rng.integers(0, V, size=self.batch_size)
        for i in range(self.seq_len):
            toks[:, i + 1] = (self.a * toks[:, i] + self.c) % V
        flip = rng.random(toks.shape) < self.noise_frac
        toks[flip] = rng.integers(0, V, size=int(flip.sum()))
        return (toks[:, :-1].astype(np.int32),
                toks[:, 1:].astype(np.int32))


class SyntheticMLM(SyntheticLM):
    """Masked-LM view of the synthetic token stream (BERT pretraining,
    BASELINE config 3): 15% of positions replaced by the [MASK] token
    (vocab_size - 1); labels hold the original token at masked positions
    and -1 elsewhere (ignored by ``masked_lm_xent``)."""

    mask_frac = 0.15

    def batch(self, step: int) -> tuple[np.ndarray, np.ndarray]:
        tokens, _ = super().batch(step)
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, step, 0xA5C])
        )
        mask = rng.random(tokens.shape) < self.mask_frac
        labels = np.where(mask, tokens, -1).astype(np.int32)
        inputs = np.where(mask, self.spec.num_classes - 1,
                          tokens).astype(np.int32)
        return inputs, labels


class TokenFileDataset(SyntheticDataset):
    """Causal-LM corpus from a token file the user brings — the "real
    data" path for reference migrants (their torch pipelines read the
    same flat-token format, e.g. nanoGPT/Megatron ``.bin`` dumps).

    ``path``: a 1-D token array, either raw ``.bin`` (``token_dtype``,
    default uint16) or ``.npy``. The file is memory-mapped — corpora far
    larger than RAM stream through the page cache; nothing is copied at
    construction. ``batch(step)`` slices ``batch_size`` windows of
    ``seq_len + 1`` tokens at (seed, step)-deterministic random offsets
    (the standard random-window LM pretraining sampler), so the
    determinism contract (same global batch on any topology) holds
    exactly as for the synthetic streams.

    ``holdout_frac > 0`` reserves the file's TAIL fraction for held-out
    evaluation: training windows draw from the head region only, eval
    requests (step >= EVAL_STEP_OFFSET) from the tail only, so eval
    tokens are never trained on. With ``holdout_frac == 0`` eval draws
    from the same (training) token range — in-sample."""

    def __init__(self, path: str, seed: int, batch_size: int, *,
                 seq_len: int, vocab_size: int,
                 token_dtype: str = "uint16",
                 holdout_frac: float = 0.0) -> None:
        super().__init__(seed, batch_size)
        self.seq_len = seq_len
        self.spec = BatchSpec((seq_len,), np.dtype(np.int32), (seq_len,),
                              np.dtype(np.int32), vocab_size)
        if not 0.0 <= holdout_frac < 1.0:
            raise ValueError(f"holdout_frac must be in [0, 1), got "
                             f"{holdout_frac}")
        if str(path).endswith(".npy"):
            self.tokens = np.load(path, mmap_mode="r")
        else:
            self.tokens = np.memmap(path, dtype=np.dtype(token_dtype),
                                    mode="r")
        if self.tokens.ndim != 1:
            raise ValueError(
                f"token file must be 1-D, got shape {self.tokens.shape}"
            )
        if len(self.tokens) < seq_len + 1:
            raise ValueError(
                f"token file has {len(self.tokens)} tokens; need at "
                f"least seq_len + 1 = {seq_len + 1}"
            )
        n = len(self.tokens)
        self._eval_start = n - int(n * holdout_frac) if holdout_frac else n
        if holdout_frac:
            # both regions must hold at least one full window
            if self._eval_start < seq_len + 1:
                raise ValueError(
                    f"holdout_frac {holdout_frac} leaves no full "
                    f"training window (train region {self._eval_start} "
                    f"tokens < seq_len + 1)"
                )
            if n - self._eval_start < seq_len + 1:
                raise ValueError(
                    f"holdout_frac {holdout_frac} reserves only "
                    f"{n - self._eval_start} tokens — not one full "
                    f"eval window (need seq_len + 1 = {seq_len + 1})"
                )

    def batch(self, step: int) -> tuple[np.ndarray, np.ndarray]:
        rng = self._rng(step)
        # windows span seq_len + 1 tokens; the largest valid start is
        # region_end - (seq_len + 1), so the exclusive high is
        # region_end - seq_len
        if step >= EVAL_STEP_OFFSET and self._eval_start < len(self.tokens):
            lo, hi = self._eval_start, len(self.tokens) - self.seq_len
        else:
            lo, hi = 0, self._eval_start - self.seq_len
        starts = rng.integers(lo, hi, size=self.batch_size)
        rows = np.stack([
            np.asarray(self.tokens[s:s + self.seq_len + 1])
            for s in starts
        ]).astype(np.int64)
        if rows.max() >= self.spec.num_classes:
            raise ValueError(
                f"token id {rows.max()} >= vocab_size "
                f"{self.spec.num_classes} — set data.vocab_size to the "
                "tokenizer's size"
            )
        return (rows[:, :-1].astype(np.int32),
                rows[:, 1:].astype(np.int32))


class ArraySampler(SyntheticDataset):
    """Epoch-shuffle / replacement sampling over an in-memory example
    index — the engine behind every finite dataset here (npz arrays,
    MNIST idx, CIFAR binaries, image folders).

    ``sample='shuffle'`` (default) walks a fresh per-epoch permutation —
    every example exactly once per epoch, torch ``DistributedSampler``
    semantics (its ``set_epoch`` reshuffle included); ``'replacement'``
    draws i.i.d. Both are (seed, step)-deterministic, preserving the
    any-topology determinism contract.

    Held-out evaluation (eval requests arrive at step >=
    EVAL_STEP_OFFSET), strongest available source first:
    - subclasses with a REAL test split (MNIST t10k, CIFAR test_batch,
      an image folder's val/ dir) pass ``n_eval_tail`` > 0: the last
      ``n_eval_tail`` rows are that split, never trained on;
    - else ``holdout_frac > 0`` reserves a seed-deterministic uniform
      row subset;
    - else eval draws from the training rows — in-sample.

    Subclasses override :meth:`_gather` when examples need per-batch
    materialisation (image decode); the default is array indexing of
    ``self.x`` / ``self.y``.
    """

    def __init__(self, x, y, seed: int, batch_size: int, *,
                 sample: str = "shuffle", holdout_frac: float = 0.0,
                 n_eval_tail: int = 0) -> None:
        super().__init__(seed, batch_size)
        if sample not in ("shuffle", "replacement"):
            raise ValueError(f"unknown sample mode {sample!r}")
        if not 0.0 <= holdout_frac < 1.0:
            raise ValueError(f"holdout_frac must be in [0, 1), got "
                             f"{holdout_frac}")
        if len(x) != len(y):
            raise ValueError(
                f"x has {len(x)} rows but y has {len(y)}"
            )
        self.sample = sample
        self.x = x
        self.y = np.asarray(y).astype(np.int32)
        n = len(self.x)
        if n_eval_tail:
            if holdout_frac:
                raise ValueError(
                    "holdout_frac is redundant when a real test split "
                    "exists (n_eval_tail > 0)"
                )
            if not 0 < n_eval_tail < n:
                raise ValueError(
                    f"n_eval_tail {n_eval_tail} out of range for {n} rows"
                )
            self._eval_rows = np.arange(n - n_eval_tail, n)
            self._train_rows = np.arange(n - n_eval_tail)
        else:
            n_eval = int(n * holdout_frac)
            if holdout_frac and (n_eval == 0 or n_eval == n):
                raise ValueError(
                    f"holdout_frac {holdout_frac} of {n} rows leaves an "
                    "empty train or eval split"
                )
            # the split is keyed on seed only (not step), so it is the
            # same partition for every batch of the run
            split = np.random.default_rng(
                np.random.SeedSequence([self.seed, 0x401D])
            ).permutation(n)
            self._eval_rows = np.sort(split[:n_eval])
            self._train_rows = np.sort(split[n_eval:])
        self._perm_cache: dict[str, tuple[int, np.ndarray]] = {}

    def _gather(self, idx: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        # fancy indexing already copies; copy=False skips a second pass
        # when x is stored as float32
        return self.x[idx].astype(np.float32, copy=False), self.y[idx]

    def _perm(self, which: str, rows: np.ndarray,
              epoch: int) -> np.ndarray:
        # pure in (seed, epoch) — cached so each step costs O(batch),
        # not an O(N) reshuffle (N can be millions of rows)
        cached = self._perm_cache.get(which)
        if cached is not None and cached[0] == epoch:
            return cached[1]
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, epoch, 0x5EAF])
        )
        perm = rows[rng.permutation(len(rows))]
        self._perm_cache[which] = (epoch, perm)
        return perm

    def batch(self, step: int) -> tuple[np.ndarray, np.ndarray]:
        if step >= EVAL_STEP_OFFSET and len(self._eval_rows):
            which, rows = "eval", self._eval_rows
            step = step - EVAL_STEP_OFFSET
        else:
            which, rows = "train", self._train_rows
        if self.sample == "replacement":
            rng = self._rng(step)
            idx = rows[rng.integers(0, len(rows), size=self.batch_size)]
        else:
            n = len(rows)
            pos = step * self.batch_size
            parts, remaining = [], self.batch_size
            while remaining:  # may straddle epoch boundaries
                epoch, within = divmod(pos, n)
                take = min(remaining, n - within)
                parts.append(
                    self._perm(which, rows, epoch)[within:within + take]
                )
                pos += take
                remaining -= take
            idx = np.concatenate(parts)
        return self._gather(idx)


class ArrayFileDataset(ArraySampler):
    """Classification data from a ``.npz`` the user brings, with arrays
    ``x`` (N, ...) and integer ``y`` (N,) — the torchvision-Dataset
    analogue for migrants with exported arrays. Sampling/holdout
    semantics: :class:`ArraySampler`."""

    def __init__(self, path: str, seed: int, batch_size: int, *,
                 sample: str = "shuffle",
                 holdout_frac: float = 0.0) -> None:
        data = np.load(path)
        try:
            x, y = data["x"], data["y"]
        except KeyError as e:
            raise ValueError(
                f"{path} must contain arrays 'x' and 'y'"
            ) from e
        super().__init__(x, y, seed, batch_size, sample=sample,
                         holdout_frac=holdout_frac)
        self.spec = BatchSpec(tuple(self.x.shape[1:]),
                              np.dtype(np.float32), (),
                              np.dtype(np.int32),
                              int(self.y.max()) + 1)


_FILE_DATASETS = ("token_file", "array_file", "mnist_idx",
                  "cifar10_bin", "image_folder")


def get_dataset(name: str, *, seed: int, batch_size: int,
                seq_len: int = 512, vocab_size: int = 32000,
                path: str = "", token_dtype: str = "uint16",
                sample: str = "shuffle", holdout_frac: float = 0.0,
                image_size: int = 224, num_workers: int = 0):
    if name in _FILE_DATASETS and not path:
        raise ValueError(f"dataset {name!r} needs data.path")
    if name in ("mnist_idx", "cifar10_bin", "image_folder"):
        from pytorch_distributed_nn_tpu.data import readers

        if name == "mnist_idx":
            return readers.MnistIdxDataset(
                path, seed, batch_size, sample=sample,
                holdout_frac=holdout_frac)
        if name == "cifar10_bin":
            return readers.Cifar10BinDataset(
                path, seed, batch_size, sample=sample,
                holdout_frac=holdout_frac)
        return readers.ImageFolderDataset(
            path, seed, batch_size, sample=sample,
            holdout_frac=holdout_frac, image_size=image_size,
            num_workers=num_workers)
    if name == "token_file":
        return TokenFileDataset(path, seed, batch_size, seq_len=seq_len,
                                vocab_size=vocab_size,
                                token_dtype=token_dtype,
                                holdout_frac=holdout_frac)
    if name == "array_file":
        return ArrayFileDataset(path, seed, batch_size, sample=sample,
                                holdout_frac=holdout_frac)
    if name == "mnist":
        return ClassTemplateImages(seed, batch_size, shape=(28, 28),
                                   num_classes=10)
    if name == "cifar10":
        return ClassTemplateImages(seed, batch_size, shape=(32, 32, 3),
                                   num_classes=10)
    if name == "imagenet_synthetic":
        return ClassTemplateImages(seed, batch_size, shape=(224, 224, 3),
                                   num_classes=1000)
    if name == "lm_synthetic":
        return SyntheticLM(seed, batch_size, seq_len=seq_len,
                           vocab_size=vocab_size)
    if name == "mlm_synthetic":
        return SyntheticMLM(seed, batch_size, seq_len=seq_len,
                            vocab_size=vocab_size)
    raise KeyError(f"unknown dataset {name!r}")
