"""Autoregressive generation with a KV cache.

Beyond the reference's scope (it is a training harness), but a framework
a reference user switches to needs an inference path. Design:

- the cache is a flax "cache" collection sized once by ``init_cache``
  (one ``cached_key``/``cached_value``/``cache_index`` per attention
  layer — :class:`nn.attention.MultiHeadAttention` with ``decode=True``);
- the prompt is consumed in ONE prefill ``apply`` (full (B, P) chunk —
  batched matmuls on the MXU, not P sequential steps);
- the token loop is ONE jitted device program (``lax.scan`` over
  sample→feed steps, cache donated): decoding is O(T) in cache reads
  instead of the O(T^2) full-context recompute, and the host dispatches
  once per generate() call, not once per token;
- sampling: greedy (``temperature=0``), temperature, and top-k — all on
  device via ``jax.random.categorical``.

Supported models: the Llama family (rotary positions are absolute via
the cache index) and TransformerLM (learned positional table offset by
a model-level cache counter). Token-identical to full-context argmax
decoding — the oracle in tests/test_generate.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_nn_tpu import obs


def shard_params_for_inference(params, mesh):
    """Place params on ``mesh`` per the TP/EP layout rules
    (parallel/sharding_rules) with no fsdp sharding — inference has no
    optimizer state to spread, and row/column-parallel weights are what
    make a model wider than one chip's HBM decodable. XLA inserts the
    Megatron all-reduces in the decode step from these layouts alone."""
    from pytorch_distributed_nn_tpu.parallel.sharding_rules import (
        path_str,
        spec_for,
    )
    from pytorch_distributed_nn_tpu.runtime.mesh import (
        AXIS_EXPERT,
        AXIS_TENSOR,
        global_device_put,
    )

    tensor = mesh.shape.get(AXIS_TENSOR, 1)
    expert = mesh.shape.get(AXIS_EXPERT, 1)
    shardings = jax.tree_util.tree_map_with_path(
        lambda kp, x: NamedSharding(
            mesh,
            spec_for(path_str(kp), tuple(x.shape), tensor=tensor,
                     expert=expert),
        ),
        params,
    )
    return global_device_put(params, shardings)


def _shard_cache(cache, mesh):
    """KV caches shard their heads dim over ``tensor`` (matching the
    q/k/v projection layout, so cache writes stay local); scalars and
    indivisible leaves replicate."""
    from pytorch_distributed_nn_tpu.runtime.mesh import (
        AXIS_TENSOR,
        global_device_put,
    )

    tensor = mesh.shape.get(AXIS_TENSOR, 1)

    def spec(x):
        # (B, T, Hkv, D) payloads and (B, T, Hkv) int8-cache scales
        # both carry heads at axis 2
        if x.ndim in (3, 4) and tensor > 1 and x.shape[2] % tensor == 0:
            return P(None, None, AXIS_TENSOR)
        return P()

    shardings = jax.tree.map(lambda x: NamedSharding(mesh, spec(x)),
                             cache)
    return global_device_put(cache, shardings)


def init_cache(model, batch_size: int, max_len: int):
    """Size the per-layer KV caches for a (batch_size, max_len) stream.

    Returns the "cache" pytree (zeros); params come from training /
    checkpoints. Shape inference only — ``jax.eval_shape`` over
    ``model.init``, so no parameters are materialized and no forward
    runs (an 8B model would otherwise allocate and discard the full
    param set here on every generate() call).
    """
    try:
        shapes = jax.eval_shape(
            lambda: model.init(
                jax.random.key(0),
                jnp.zeros((batch_size, max_len), jnp.int32),
                train=False, decode=True,
            )
        )
    except TypeError as e:  # no `decode` kwarg on this model family
        raise ValueError(
            f"{type(model).__name__} has no decode cache support"
        ) from e
    if "cache" not in shapes:
        raise ValueError(
            f"{type(model).__name__} has no decode cache support"
        )
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        shapes["cache"])


def _apply_decode(model, params, cache, tokens):
    """One (B, T) decode chunk: returns ((B, V) next-token logits,
    updated cache). last_only skips the vocab projection for all but
    the final position (the only row generation consumes)."""
    logits, mutated = model.apply(
        {"params": params, "cache": cache}, tokens,
        train=False, decode=True, last_only=True, mutable=["cache"],
    )
    return logits[:, -1, :], mutated["cache"]


_decode_step = functools.partial(jax.jit, static_argnums=(0,),
                                 donate_argnums=(2,))(_apply_decode)


def _apply_prefill_ragged(model, params, cache, tokens, lengths):
    """Ragged prefill: ``tokens`` (B, P) LEFT-ALIGNED rows (row i's real
    prompt in columns [0, lengths[i]); columns beyond are don't-care).
    Every row writes its KV from cache slot 0 (``cache_positions`` = 0),
    and the per-position causal mask keeps slots >= lengths[i] out of
    every consumed attention row, so each row computes exactly its
    sequential prefill. Returns ((B, V) logits at each row's LAST real
    position, cache). Full logits are materialized (not ``last_only``)
    because "last" differs per row — fine at serving batch sizes; the
    (P-1) extra head rows are the price of one fused prefill."""
    zeros = jnp.zeros((tokens.shape[0],), jnp.int32)
    logits, mutated = model.apply(
        {"params": params, "cache": cache}, tokens,
        train=False, decode=True, mutable=["cache"],
        cache_positions=zeros,
    )
    last = (lengths.astype(jnp.int32) - 1)[:, None, None]
    next_logits = jnp.take_along_axis(logits, last, axis=1)[:, 0, :]
    return next_logits, mutated["cache"]


prefill_ragged = functools.partial(
    jax.jit, static_argnums=(0,), donate_argnums=(2,)
)(_apply_prefill_ragged)


def _apply_decode_ragged(model, params, cache, tokens, positions):
    """One per-row decode step: ``tokens`` (B,) int32 next tokens,
    ``positions`` (B,) int32 per-row cache depths (row i's token lands
    in cache slot positions[i] and attends slots [0, positions[i]]).
    The shared scalar cache_index is untouched — rows at different
    depths share one batch, which is what continuous batching needs.
    Returns ((B, V) next-token logits, cache)."""
    logits, mutated = model.apply(
        {"params": params, "cache": cache}, tokens[:, None],
        train=False, decode=True, last_only=True, mutable=["cache"],
        cache_positions=positions.astype(jnp.int32),
    )
    return logits[:, -1, :], mutated["cache"]


decode_step_ragged = functools.partial(
    jax.jit, static_argnums=(0,), donate_argnums=(2,)
)(_apply_decode_ragged)


@functools.partial(jax.jit, static_argnums=(0, 5, 7, 8, 9),
                   donate_argnums=(2,))
def _decode_loop(model, params, cache, next_logits, rng, n_steps,
                 temperature, top_k, eos_token, top_p):
    """The whole autoregressive loop as ONE device program: ``lax.scan``
    over decode steps (sample → feed → next logits). One dispatch for
    all ``n_steps`` tokens — per-token host round-trips would otherwise
    dominate wall-clock when the chip sits behind a network tunnel (and
    still cost ~dispatch-latency × n_steps locally). ``temperature`` is
    a traced operand (per-request values don't recompile); only
    n_steps/top_k/eos_token key the compile cache. Returns (n_steps, B)
    sampled tokens."""

    def step(carry, _):
        next_logits, cache, rng, done = carry
        rng, step_rng = jax.random.split(rng)
        tok = _sample(next_logits, temperature=temperature, top_k=top_k,
                      rng=step_rng, top_p=top_p)
        if eos_token is not None:
            tok = jnp.where(done, eos_token, tok)
            done = done | (tok == eos_token)
        tok = tok.astype(jnp.int32)
        # the final iteration's decode is one step of dead compute
        # (its logits are never sampled) but keeps the scan uniform;
        # the cache is sized for it (index ends at P + n_steps)
        next_logits, cache = _apply_decode(model, params, cache,
                                           tok[:, None])
        return (next_logits, cache, rng, done), tok

    done0 = jnp.zeros((next_logits.shape[0],), bool)
    (_, final_cache, _, _), toks = jax.lax.scan(
        step, (next_logits, cache, rng, done0), None, length=n_steps
    )
    # the caller discards final_cache, but RETURNING it is what lets
    # the donated input cache alias an output buffer — without it XLA
    # warns "donated buffers were not usable" and the loop transiently
    # holds TWO cache copies (268 MB at the 8B's b=8/T=256, real HBM)
    return toks, final_cache


@functools.partial(jax.jit, static_argnums=(0, 5, 7, 8, 9),
                   donate_argnums=(2,))
def _decode_loop_ragged(model, params, cache, next_logits, rng, n_steps,
                        temperature, top_k, eos_token, top_p, lengths):
    """Ragged twin of :func:`_decode_loop`: the scan carry additionally
    holds per-row cache depths (starting at the prompt lengths), and
    each step feeds through the per-row decode apply. Same fused
    one-dispatch property; ``lengths`` is traced so different ragged
    batches share one compile."""

    def step(carry, _):
        next_logits, cache, rng, done, pos = carry
        rng, step_rng = jax.random.split(rng)
        tok = _sample(next_logits, temperature=temperature, top_k=top_k,
                      rng=step_rng, top_p=top_p)
        if eos_token is not None:
            tok = jnp.where(done, eos_token, tok)
            done = done | (tok == eos_token)
        tok = tok.astype(jnp.int32)
        next_logits, cache = _apply_decode_ragged(model, params, cache,
                                                  tok, pos)
        return (next_logits, cache, rng, done, pos + 1), tok

    done0 = jnp.zeros((next_logits.shape[0],), bool)
    (_, final_cache, _, _, _), toks = jax.lax.scan(
        step, (next_logits, cache, rng, done0,
               lengths.astype(jnp.int32)), None, length=n_steps
    )
    return toks, final_cache


def _sample(logits, *, temperature, top_k: int, rng, top_p: float = 0.0):
    """logits (B, V) -> tokens (B,). ``temperature`` may be a traced
    scalar OR a traced (B,) per-row vector (0 selects greedy via
    jnp.where — top-k/top-p membership is temperature-invariant, so
    filtering before scaling is equivalent), which keeps per-request
    temperatures from recompiling the decode scan. A (B,) temperature
    scales row-wise and picks greedy row-wise, so mixed greedy+sampled
    batches compose with the top_p mask at batch granularity.
    ``top_k``/``top_p`` stay static (top_k needs a static k; p changes
    the masking structure)."""
    greedy = jnp.argmax(logits, axis=-1)
    if rng is None:
        return greedy
    if top_k > 0:
        k = min(top_k, logits.shape[-1])
        kth = jax.lax.top_k(logits, k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p > 0.0:
        # nucleus: keep the smallest prefix of the sorted distribution
        # with cumulative mass >= top_p (the first token always stays)
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = cum - probs < top_p  # mass BEFORE this token < p
        cutoff = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
            keepdims=True,
        )
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    temperature = jnp.asarray(temperature)
    # a (B,) vector must scale along the batch axis, not broadcast
    # against (B, V)'s vocab axis — the scalar shape is unchanged
    scale_t = (temperature[:, None] if temperature.ndim == 1
               else temperature)
    scaled = logits / jnp.maximum(scale_t, 1e-6)
    sampled = jax.random.categorical(rng, scaled, axis=-1)
    return jnp.where(temperature == 0.0, greedy, sampled)


def generate(model, params, prompt, max_new_tokens: int, *,
             temperature: float = 0.0, top_k: int = 0,
             top_p: float = 0.0, rng=None,
             eos_token: int | None = None, mesh=None,
             prefill_chunk: int = 0, prompt_lengths=None):
    """Generate continuations for ``prompt`` (B, P) int32.

    Returns (B, P + max_new_tokens) tokens (prompt included). With
    ``eos_token`` set, sequences that emit it keep it and then pad with
    it (the batch still runs max_new_tokens steps).

    ``prompt_lengths``: ragged batches. (B,) ints — row i's real prompt
    is the LAST prompt_lengths[i] columns (left-padding convention, pad
    values are don't-care). Rows are realigned internally and decoded
    via per-row cache positions; greedy output for each row is
    bit-identical to running that row alone through generate()
    (tests/test_generate.py golden test). The returned array keeps the
    padded prompt prefix as passed: generated tokens for every row live
    in columns [P, P + max_new_tokens).

    ``mesh``: distributed decoding — params are laid out tensor/expert-
    parallel (:func:`shard_params_for_inference`), the KV cache shards
    its heads dim to match, and the jitted decode program runs SPMD over
    the mesh with XLA-inserted collectives. Token-identical to the
    single-device path.

    ``prefill_chunk``: consume the prompt in chunks of this many tokens
    instead of one apply. One-shot prefill scores (P, P); chunked
    prefill bounds live attention scores at (chunk, P) — the difference
    between a 32k-token prompt fitting or not. Token-identical either
    way (the decode cache makes chunked prefill exact).
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim != 2 or prompt.shape[1] < 1:
        raise ValueError(f"prompt must be (B, P>=1), got {prompt.shape}")
    if max_new_tokens < 0:
        raise ValueError(
            f"max_new_tokens must be >= 0, got {max_new_tokens}"
        )
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if not 0.0 <= top_p <= 1.0:
        raise ValueError(f"top_p must be in [0, 1], got {top_p}")
    if prefill_chunk < 0:
        raise ValueError(
            f"prefill_chunk must be >= 0, got {prefill_chunk}"
        )
    if temperature > 0.0 and rng is None:
        raise ValueError("sampling (temperature > 0) needs an rng key")
    B, P_len = prompt.shape
    if prompt_lengths is not None:
        lens_host = np.asarray(prompt_lengths, dtype=np.int64)
        if lens_host.shape != (B,):
            raise ValueError(
                f"prompt_lengths must be ({B},), got {lens_host.shape}"
            )
        if lens_host.min() < 1 or lens_host.max() > P_len:
            raise ValueError(
                f"prompt_lengths must be in [1, {P_len}], got "
                f"[{lens_host.min()}, {lens_host.max()}]"
            )
        if mesh is not None:
            raise ValueError(
                "ragged prompts (prompt_lengths) are not supported with "
                "mesh sharding yet — shard the params and run the "
                "uniform path, or batch equal-length rows"
            )
        if prefill_chunk:
            raise ValueError(
                "prompt_lengths and prefill_chunk are mutually "
                "exclusive (ragged prefill is one fused apply)"
            )
    if max_new_tokens == 0:
        return prompt
    if prompt_lengths is not None:
        return _generate_ragged(
            model, params, prompt, max_new_tokens, lens_host,
            temperature=temperature, top_k=top_k, top_p=top_p, rng=rng,
            eos_token=eos_token,
        )
    total = P_len + max_new_tokens
    cache = init_cache(model, B, total)
    if mesh is not None:
        params = shard_params_for_inference(params, mesh)
        cache = _shard_cache(cache, mesh)
        from pytorch_distributed_nn_tpu.runtime.mesh import (
            global_device_put,
        )

        prompt = global_device_put(prompt, NamedSharding(mesh, P()))

    # prefill: the whole prompt in one chunk, or bounded chunks for
    # long prompts (each chunk attends to the cache prefix, so live
    # scores are (chunk, filled) instead of (P, P))
    with obs.span("inference/prefill", batch=B, prompt_len=P_len):
        if prefill_chunk and prefill_chunk < P_len:
            pos = 0
            while pos < P_len:
                chunk = prompt[:, pos:pos + prefill_chunk]
                next_logits, cache = _decode_step(model, params, cache,
                                                  chunk)
                pos += chunk.shape[1]
        else:
            next_logits, cache = _decode_step(model, params, cache,
                                              prompt)

    # greedy ignores the key; pass a constant so the trace is uniform
    rng0 = rng if rng is not None else jax.random.key(0)
    # span covers dispatch of the fused scan, not device completion —
    # callers that fence (bench) see the true decode window in-trace
    with obs.span("inference/decode_loop", batch=B,
                  new_tokens=max_new_tokens):
        toks, _ = _decode_loop(model, params, cache, next_logits, rng0,
                               max_new_tokens, jnp.float32(temperature),
                               int(top_k), eos_token, float(top_p))
    obs.get_registry().counter(
        "inference_tokens_total", "tokens generated (dispatched)").inc(
        B * max_new_tokens)
    return jnp.concatenate([prompt, toks.T.astype(jnp.int32)], axis=1)


def _generate_ragged(model, params, prompt, max_new_tokens, lens_host,
                     *, temperature, top_k, top_p, rng, eos_token):
    """The ragged-batch body of :func:`generate` (validated inputs).

    Left-padded rows are realigned to left-ALIGNED internally (row i's
    prompt occupies cache slots [0, L_i)), prefilled in one per-row
    apply, then decoded by the ragged scan with per-row cache depths.
    The causal-by-slot mask zeroes every don't-care slot exactly
    (softmax weight exp(-1e30 - max) underflows to 0.0), so each row's
    float math is the sequential row's float math — bit-identical
    greedy decoding, not just approximately equal."""
    B, P_len = prompt.shape
    lengths = jnp.asarray(lens_host, jnp.int32)
    # realign: aligned[i, j] = prompt[i, (j + P - L_i) % P] puts row
    # i's first real token at column 0 and wraps its padding to the
    # tail (which the mask then excludes from all consumed rows)
    shift = (jnp.arange(P_len)[None, :]
             + (P_len - lengths)[:, None]) % P_len
    aligned = jnp.take_along_axis(prompt, shift, axis=1)
    cache = init_cache(model, B, P_len + max_new_tokens)
    with obs.span("inference/prefill", batch=B, prompt_len=P_len,
                  ragged=True):
        next_logits, cache = prefill_ragged(model, params, cache,
                                            aligned, lengths)
    rng0 = rng if rng is not None else jax.random.key(0)
    with obs.span("inference/decode_loop", batch=B,
                  new_tokens=max_new_tokens):
        toks, _ = _decode_loop_ragged(
            model, params, cache, next_logits, rng0, max_new_tokens,
            jnp.float32(temperature), int(top_k), eos_token,
            float(top_p), lengths)
    obs.get_registry().counter(
        "inference_tokens_total", "tokens generated (dispatched)").inc(
        B * max_new_tokens)
    return jnp.concatenate([prompt, toks.T.astype(jnp.int32)], axis=1)
