from pytorch_distributed_nn_tpu.inference.generate import (  # noqa: F401
    generate,
    init_cache,
)
