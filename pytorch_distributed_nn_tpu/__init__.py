"""pytorch_distributed_nn_tpu — a TPU-native distributed training framework.

A brand-new framework with the capability surface of the reference repo
``chao1224/pytorch_distributed_nn`` (a pure-Python harness over
``torch.distributed``: DDP bucketed allreduce, parameter broadcast, p2p
pipeline stages, all-gather/reduce-scatter sharded DP), re-designed
TPU-first:

- process bootstrap via ``jax.distributed`` instead of ``torchrun``/NCCL
  (reference capability: ``dist.init_process_group`` — see SURVEY.md §1),
- data-parallel gradient allreduce via ``jax.lax.psum`` over ICI instead of
  NCCL ring allreduce (SURVEY.md §2c),
- sharded DP via ``NamedSharding`` so XLA emits all-gather/reduce-scatter
  (SURVEY.md §3.4),
- pipeline stages via ``shard_map`` + ``lax.ppermute`` instead of
  ``dist.send/recv`` (SURVEY.md §3.3),
- tensor/sequence/context parallelism and ring attention as first-class
  mesh axes (SURVEY.md §2c),
- Pallas kernels for the hot ops and a C++ native runtime substrate
  (rendezvous store, host data pipeline) where the reference leaned on
  c10d's C++ core.

The reference mount was empty at survey time (SURVEY.md provenance note);
parity targets come from /root/repo/BASELINE.json.
"""

from pytorch_distributed_nn_tpu.version import __version__

__all__ = ["__version__"]
