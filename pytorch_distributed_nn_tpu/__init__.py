"""pytorch_distributed_nn_tpu — a TPU-native distributed training framework.

A brand-new framework with the capability surface of the reference repo
``chao1224/pytorch_distributed_nn`` (a pure-Python harness over
``torch.distributed``: DDP bucketed allreduce, parameter broadcast, p2p
pipeline stages, all-gather/reduce-scatter sharded DP), re-designed
TPU-first:

- process bootstrap via ``jax.distributed`` instead of ``torchrun``/NCCL
  (reference capability: ``dist.init_process_group`` — see SURVEY.md §1),
- data-parallel gradient allreduce via ``jax.lax.psum`` over ICI instead of
  NCCL ring allreduce (SURVEY.md §2c),
- sharded DP via ``NamedSharding`` so XLA emits all-gather/reduce-scatter
  (SURVEY.md §3.4),
- pipeline stages via ``shard_map`` + ``lax.ppermute`` instead of
  ``dist.send/recv`` (SURVEY.md §3.3),
- tensor/sequence/context parallelism and ring attention as first-class
  mesh axes (SURVEY.md §2c),
- Pallas kernels for the hot ops and a C++ native runtime substrate
  (rendezvous store, host data pipeline) where the reference leaned on
  c10d's C++ core.

The reference mount was empty at survey time (SURVEY.md provenance note);
parity targets come from /root/repo/BASELINE.json.
"""

from pytorch_distributed_nn_tpu.version import __version__


def _install_jax_compat() -> None:
    """Back-fill the small slice of newer-jax API this codebase uses
    (``jax.shard_map`` with ``check_vma``/``axis_names``,
    ``jax.lax.axis_size``, ``jax.lax.pcast``) on older jax installs,
    where they live at ``jax.experimental.shard_map.shard_map``
    (``check_rep``/``auto`` spelling) and ``jax.core.axis_frame``.
    Attribute-level shim only — no behavior changes on jax versions
    that already have the API."""
    import functools

    import jax
    from jax import lax

    if not hasattr(lax, "axis_size"):
        import jax.core as _core

        def _axis_size(axis_name):
            # 0.4.x: axis_frame(name) resolves to the trace-time size
            return _core.axis_frame(axis_name)

        lax.axis_size = _axis_size

    if not hasattr(lax, "pcast"):
        # newer jax: pcast only re-tags the varying-manual-axes type
        # (no data movement). Old jax tracks replication only under
        # check_rep, which the shim below disables wherever auto axes
        # are in play — identity is the faithful translation.
        def _pcast(x, axis_name=None, *, to=None):
            return x

        lax.pcast = _pcast

    if hasattr(jax, "shard_map"):
        return
    try:
        from jax.experimental.shard_map import shard_map as _sm
    except ImportError:  # very old jax: nothing to shim with
        return

    @functools.wraps(_sm)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if "axis_names" in kwargs:
            # newer API: axis_names = the MANUAL axes; older API takes
            # the complement as `auto`, and only supports it with
            # replication checking off
            manual = frozenset(kwargs.pop("axis_names"))
            mesh = kwargs.get("mesh", args[1] if len(args) > 1 else None)
            auto = frozenset(mesh.axis_names) - manual
            if auto:
                kwargs["auto"] = auto
                kwargs["check_rep"] = False
        return _sm(*args, **kwargs)

    jax.shard_map = shard_map


_install_jax_compat()

__all__ = ["__version__"]
