"""Config / flag system.

The reference configures each trainer with argparse flags (``--rank``,
``--world-size``, ``--backend``, ``--lr``, …; SURVEY.md §5 "Config/flag
system"). Here configs are typed dataclasses with dotted CLI overrides
(``--optim.lr=0.1``), and the five benchmark configs from
/root/repo/BASELINE.json:6-12 are named presets.
"""

from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field
from typing import Any

from pytorch_distributed_nn_tpu.runtime.mesh import MeshSpec


@dataclass
class OptimConfig:
    name: str = "sgd"  # sgd | momentum | adam | adamw | adafactor | lamb | lion
    lr: float = 0.01
    momentum: float = 0.9
    weight_decay: float = 0.0
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    grad_clip_norm: float = 0.0  # 0 = off
    warmup_steps: int = 0
    schedule: str = "constant"  # constant | cosine | linear | step
    # schedule="step" (torch StepLR): decay by step_gamma at these
    # fractions of the post-warmup run
    step_milestones: tuple[float, ...] = (0.5, 0.75)
    step_gamma: float = 0.1
    # skip weight decay on 1-D params (norm scales/biases) — the usual
    # LLM recipe; False reproduces torch's decay-everything default
    decay_mask_norms: bool = False
    # store momentum/adam/adamw/lion first moments in this dtype
    # ("" = param dtype): "bfloat16" halves that slice of optimizer HBM
    # (rejected for optimizers without moment-dtype control)
    mu_dtype: str = ""


@dataclass
class DataConfig:
    # mnist | cifar10 | imagenet_synthetic | lm_synthetic | mlm_synthetic
    # | token_file (causal LM from a memory-mapped .bin/.npy token dump)
    # | array_file (classification from a .npz with arrays x, y)
    # | mnist_idx (LeCun idx files; t10k-* pair = real eval split)
    # | cifar10_bin (data_batch_*.bin; test_batch.bin = real eval split)
    # | image_folder (torchvision layout root/<class>/<img>, lazy PIL
    #   decode; train/ + val/ dirs honored as the split)
    dataset: str = "mnist"
    path: str = ""  # file/directory for the file-backed datasets
    image_size: int = 224  # image_folder: decode target (short side +
    #                        center crop, torchvision eval transform)
    token_dtype: str = "uint16"  # raw .bin token width (token_file)
    # array_file sampling: 'shuffle' (per-epoch permutation, torch
    # DistributedSampler semantics) or 'replacement' (i.i.d.)
    sample: str = "shuffle"
    # token_file/array_file: fraction of the file reserved for held-out
    # eval (0 = none; file-dataset eval is then IN-SAMPLE — it reports
    # training-set performance). Synthetic streams are infinite and
    # always genuinely held out.
    holdout_frac: float = 0.0
    batch_size: int = 128  # global batch size
    seq_len: int = 512
    vocab_size: int = 32000
    prefetch: int = 2  # background host batches kept ready (0 = sync)
    # decode threads per batch for image_folder (torch DataLoader
    # num_workers semantics: 0 = inline, -1 = one per core capped 16;
    # PIL/libjpeg releases the GIL so threads scale across cores)
    num_workers: int = -1


@dataclass
class ModelConfig:
    name: str = "mlp"  # mlp | lenet | resnet50 | bert_base | transformer_lm | llama3_8b
    dtype: str = "float32"  # param dtype
    compute_dtype: str = "bfloat16"
    remat: bool = False  # jax.checkpoint on blocks
    # offload the remat block boundaries to pinned host RAM instead of
    # HBM (XLA host-offload; needs remat=True; llama only for now) —
    # the long-context enabler: HBM holds one layer's recompute, not
    # every boundary
    remat_offload: bool = False
    extra: dict[str, Any] = field(default_factory=dict)


@dataclass
class ParallelConfig:
    strategy: str = "dp"  # single | dp | zero | pipeline | ps
    # DDP-style bucket controller (SURVEY.md §2b Reducer row):
    bucket_mb: float = 25.0
    overlap: bool = True
    zero_stage: int = 3  # 1 = optimizer-state shard; 3 = params too
    microbatches: int = 1  # pipeline microbatching
    # gradient accumulation (single/dp/zero): split the global batch into
    # this many sequentially-scanned microbatches per optimizer step —
    # ~grad_accum× lower peak activation memory. Identical math to
    # accum=1 for deterministic stateless models; dropout masks are
    # re-drawn per microbatch and BatchNorm stats update per microbatch
    # (torch-accumulation-loop semantics), so those curves differ
    # slightly from the one-shot step
    grad_accum: int = 1
    # "gpipe": AD-transposed fill-drain — simplest, but the scan
    # transpose saves residuals for every in-flight tick, so activation
    # memory grows with `microbatches`. "1f1b": PipeDream-flush with a
    # manual per-stage backward (parallel/pipeline.py::_make_1f1b_step)
    # — activation memory bounded by ~2*stages. All three schedules
    # support dropout (shared deterministic rng stream; gpipe and 1f1b
    # draw bit-identical masks).
    # "interleaved": Megatron virtual-chunk 1F1B — `pipe_chunks` chunks
    # per device round-robin over virtual stages, pipeline bubble cut
    # to ~1/pipe_chunks of 1f1b's at the cost of more in-flight
    # activations and 2x ppermute traffic (full rings).
    pipeline_schedule: str = "gpipe"
    # virtual chunks per device for pipeline_schedule='interleaved'
    # (model layers must divide stages x chunks; microbatches must
    # divide by stages — Megatron's group structure)
    pipe_chunks: int = 1
    quantized_allreduce: str = ""  # "" | "bf16" | "int8" (EQuARX-style)


@dataclass
class TrainConfig:
    preset: str = ""
    seed: int = 0
    steps: int = 100
    # device-side training loop (train/multistep.py): fuse this many
    # optimizer steps into ONE dispatch via lax.scan. Identical math to
    # k sequential steps on the same batches; checkpoint/eval cadences
    # round UP to the next dispatch boundary (the device program is not
    # interruptible mid-scan); per-step losses still log via the scan's
    # stacked metrics. The dispatch-latency amortizer for small models
    # and/or a tunneled chip (r3: mlp 27x).
    multistep_k: int = 1
    # 0 = each fused step trains on a FRESH batch (k batches stacked and
    # transferred per dispatch — the production setting). N > 0 = cycle
    # a fixed pool of N device-resident batches inside the scan:
    # repeats data, which is wrong for real training but exactly what a
    # device-rate benchmark wants (bench.py --multistep sets 4).
    multistep_pool: int = 0
    log_every: int = 10
    eval_every: int = 0  # 0 = no eval; else eval every N steps
    eval_batches: int = 8  # batches per eval pass (held-out seed stream)
    # chunk the LM softmax-xent over T (tokens per chunk; 0 = dense
    # logits). At long context the (B, T, V) logits are the HBM
    # limiter; chunking keeps one (B, chunk, V) block live instead.
    xent_chunk: int = 0
    # torch CrossEntropyLoss(label_smoothing=...) semantics; not
    # combinable with xent_chunk
    label_smoothing: float = 0.0
    checkpoint_dir: str = ""
    checkpoint_every: int = 0
    resume: bool = True
    profile_dir: str = ""
    # structured JSONL metrics (utils/metrics.MetricsLogger): every
    # log_every step + every eval + goodput breakdowns as machine-
    # readable events, emitted by the coordinator only ("" = off)
    metrics_path: str = ""
    # Prometheus textfile exposition (obs/registry.py): the process
    # registry (counters/gauges/histograms, goodput, mesh topology,
    # heartbeat state) written here at log cadence and on close
    # ("" = off) — node_exporter textfile-collector layout
    prom_path: str = ""
    mesh: MeshSpec = field(default_factory=MeshSpec)
    optim: OptimConfig = field(default_factory=OptimConfig)
    data: DataConfig = field(default_factory=DataConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)

    def override(self, **dotted: Any) -> "TrainConfig":
        cfg = copy.deepcopy(self)  # nested sub-configs must not alias self's
        for key, value in dotted.items():
            _set_dotted(cfg, key.replace("-", "_"), value)
        return cfg


def _set_dotted(obj: Any, dotted: str, value: Any) -> None:
    parts = dotted.split(".")
    for part in parts[:-1]:
        obj = getattr(obj, part)
    leaf = parts[-1]
    if not hasattr(obj, leaf):
        raise AttributeError(f"unknown config field {dotted!r}")
    current = getattr(obj, leaf)
    if current is not None and not isinstance(value, type(current)):
        if isinstance(current, bool):
            value = str(value).lower() in ("1", "true", "yes", "on")
        elif isinstance(current, (int, float)):
            value = type(current)(value)
        elif isinstance(current, dict):
            value = json.loads(value)  # e.g. --model.extra '{"d_model":64}'
        elif isinstance(current, (tuple, list)):
            # e.g. --optim.step_milestones '[0.3, 0.6, 0.9]'
            value = type(current)(json.loads(value))
    setattr(obj, leaf, value)


def parse_overrides(argv: list[str]) -> dict[str, str]:
    """Parse ``--a.b=c`` / ``--a.b c`` style CLI overrides."""
    out: dict[str, str] = {}
    i = 0
    while i < len(argv):
        arg = argv[i]
        if not arg.startswith("--"):
            raise ValueError(f"unexpected argument {arg!r}")
        arg = arg[2:]
        if "=" in arg:
            key, value = arg.split("=", 1)
        else:
            if i + 1 >= len(argv):
                raise ValueError(f"flag --{arg} expects a value")
            key, value = arg, argv[i + 1]
            i += 1
        out[key] = value
        i += 1
    return out


# ---------------------------------------------------------------------------
# The five benchmark presets (BASELINE.json "configs", lines 6-12).
# ---------------------------------------------------------------------------

def _mlp_mnist() -> TrainConfig:
    # Config 1: "2-layer MLP on MNIST, single process (gloo backend, CPU)".
    # Gloo-on-CPU maps to the XLA host platform (SURVEY.md §4).
    return TrainConfig(
        preset="mlp_mnist",
        steps=200,
        optim=OptimConfig(name="momentum", lr=0.01),
        data=DataConfig(dataset="mnist", batch_size=128),
        model=ModelConfig(name="mlp", compute_dtype="float32"),
        parallel=ParallelConfig(strategy="dp"),
    )


def _lenet_cifar10() -> TrainConfig:
    # The reference's classic small-net config (SURVEY.md §2a Models row
    # [R]: "LeNet-ish CNN on MNIST/CIFAR-10") — not one of the five
    # BASELINE configs, kept as a named preset for parity breadth.
    return TrainConfig(
        preset="lenet_cifar10",
        steps=200,
        optim=OptimConfig(name="momentum", lr=0.05),
        data=DataConfig(dataset="cifar10", batch_size=128),
        model=ModelConfig(name="lenet", compute_dtype="float32"),
        parallel=ParallelConfig(strategy="dp"),
    )


def _resnet50_dp() -> TrainConfig:
    # Config 2: "ResNet-50 / ImageNet, pure data-parallel DDP allreduce".
    return TrainConfig(
        preset="resnet50_dp",
        steps=100,
        optim=OptimConfig(name="momentum", lr=0.1, weight_decay=1e-4,
                          warmup_steps=5, schedule="cosine"),
        data=DataConfig(dataset="imagenet_synthetic", batch_size=1024),
        model=ModelConfig(name="resnet50"),
        parallel=ParallelConfig(strategy="dp", bucket_mb=25.0, overlap=True),
    )


def _bert_base_buckets() -> TrainConfig:
    # Config 3: "BERT-base pretraining, large fused gradient buckets".
    return TrainConfig(
        preset="bert_base_buckets",
        steps=100,
        optim=OptimConfig(name="adamw", lr=1e-4, weight_decay=0.01,
                          warmup_steps=10, schedule="linear"),
        data=DataConfig(dataset="mlm_synthetic", batch_size=256, seq_len=128,
                        vocab_size=30522),
        model=ModelConfig(name="bert_base"),
        # dp_explicit so the named "large fused gradient buckets" actually
        # run through the bucket controller (ops/buckets.py)
        parallel=ParallelConfig(strategy="dp_explicit", bucket_mb=100.0,
                                overlap=True),
    )


def _transformer_lm_pp() -> TrainConfig:
    # Config 4: "Transformer-LM pipeline-parallel (send/recv p2p)".
    return TrainConfig(
        preset="transformer_lm_pp",
        steps=50,
        mesh=MeshSpec(pipe=4, data=-1),
        optim=OptimConfig(name="adam", lr=3e-4, warmup_steps=10,
                          schedule="cosine"),
        data=DataConfig(dataset="lm_synthetic", batch_size=64, seq_len=1024),
        model=ModelConfig(name="transformer_lm", remat=True),
        parallel=ParallelConfig(strategy="pipeline", microbatches=8,
                                pipeline_schedule="gpipe"),
    )


def _llama3_8b_zero() -> TrainConfig:
    # Config 5: "Llama-3-8B sharded data-parallel (allgather params +
    # reduce-scatter grads)".
    return TrainConfig(
        preset="llama3_8b_zero",
        steps=20,
        mesh=MeshSpec(fsdp=-1, data=1),
        optim=OptimConfig(name="adamw", lr=3e-4, weight_decay=0.1,
                          grad_clip_norm=1.0, warmup_steps=10,
                          schedule="cosine"),
        data=DataConfig(dataset="lm_synthetic", batch_size=16, seq_len=4096,
                        vocab_size=128256),
        model=ModelConfig(name="llama3_8b", remat=True),
        parallel=ParallelConfig(strategy="zero", zero_stage=3),
        # at V=128k the dense (B, T, V) f32 logits + their cotangent are
        # the per-chip HBM limiter (~4 GiB at B=16/T=4096 over 16 chips
        # — scripts/validate_8b_layout.py); chunking keeps one
        # (B, 2048, V) block live. Falls back to dense when T <= chunk
        # (the scaled single-chip bench).
        xent_chunk=2048,
    )


def _llama3_longcontext() -> TrainConfig:
    # Beyond the reference (SURVEY.md §5 "Long-context" row): 32k-token
    # causal-LM training. Single chip: Pallas flash attention (blockwise
    # fwd + bwd, never materializing the (T, T) scores) + remat; on a
    # pod, add mesh.seq for ring-attention context parallelism.
    return TrainConfig(
        preset="llama3_longcontext",
        steps=10,
        mesh=MeshSpec(seq=1, data=-1),
        optim=OptimConfig(name="adamw", lr=1e-4, weight_decay=0.1,
                          grad_clip_norm=1.0, warmup_steps=2,
                          schedule="cosine"),
        data=DataConfig(dataset="lm_synthetic", batch_size=1,
                        seq_len=32768, vocab_size=32000),
        # head_dim 128 = the REAL Llama-3 per-head geometry (4096/32).
        # The r1-r3 stand-in used 16 heads at d=1024 (head_dim 64),
        # which half-fills the MXU contraction in every attention
        # matmul — measured r4 at T=32k fwd+bwd: 165 ms vs 102 ms for
        # the same H*D with head_dim 128 (1.62x). Same param count,
        # same FLOPs, realistic kernel shape.
        model=ModelConfig(name="llama3_8b", remat=True,
                          extra=dict(num_layers=8, d_model=1024,
                                     num_heads=8, num_kv_heads=4,
                                     mlp_dim=3584, vocab_size=32000)),
        parallel=ParallelConfig(strategy="dp"),
        # at T=32k the (T, vocab) logits are the HBM limiter (dense
        # f32 logits + grads OOM a 16 GB chip at vocab 32k); the
        # chunked xent keeps one (B, 2048, V) block live instead
        xent_chunk=2048,
    )


def _llama3_longcontext_96k() -> TrainConfig:
    # SURVEY.md §5 names 32k-512k; this preset TRAINS at 96k tokens on
    # ONE chip — the longest length with reliable headroom on a
    # tunnel-attached v5e (measured r3: 96k trains at ~12.6 s/step and
    # 112k still fits, but 120k+ exhausts the runtime's ~9.5 GiB
    # effective step budget even though compile-time analysis says
    # 10.25 GiB total at 128k; see docs/design.md "host offload").
    # Beyond one chip, 128k+ runs the dryrun-proven ring/seq-parallel
    # mesh path, and 512k is covered at kernel level by
    # scripts/validate_tpu_kernels.py's long-context check.
    # Same scaled-llama stand-in as llama3_longcontext; the streamed
    # flash kernels keep attention VMEM/HBM T-independent, remat holds
    # layer boundaries only, and chunked xent bounds the logits.
    cfg = _llama3_longcontext()
    cfg.preset = "llama3_longcontext_96k"
    cfg.data.seq_len = 98304
    cfg.steps = 5
    return cfg


def _moe_lm_ep() -> TrainConfig:
    # Beyond the reference (SURVEY.md §2c EP row): mixture-of-experts LM,
    # experts sharded over the `expert` mesh axis, token dispatch via the
    # XLA all-to-all the SPMD partitioner derives from the layout.
    return TrainConfig(
        preset="moe_lm_ep",
        steps=50,
        mesh=MeshSpec(expert=-1, data=1),
        optim=OptimConfig(name="adamw", lr=3e-4, weight_decay=0.1,
                          warmup_steps=10, schedule="cosine"),
        data=DataConfig(dataset="lm_synthetic", batch_size=32, seq_len=1024),
        # no remat: this MoE fits activations at any topology (experts
        # shard over the expert axis, batch over data) and recompute
        # costs 13% measured throughput (r3 A/B: 43.6 -> 49.3
        # samples/s/chip, 40.3% MFU); override model.remat=true for
        # bigger variants
        model=ModelConfig(name="moe_lm", remat=False),
        parallel=ParallelConfig(strategy="zero", zero_stage=3),
    )


PRESETS = {
    "mlp_mnist": _mlp_mnist,
    "lenet_cifar10": _lenet_cifar10,
    "moe_lm_ep": _moe_lm_ep,
    "llama3_longcontext": _llama3_longcontext,
    "llama3_longcontext_96k": _llama3_longcontext_96k,
    "resnet50_dp": _resnet50_dp,
    "bert_base_buckets": _bert_base_buckets,
    "transformer_lm_pp": _transformer_lm_pp,
    "llama3_8b_zero": _llama3_8b_zero,
}


def get_config(preset: str, **overrides: Any) -> TrainConfig:
    if preset not in PRESETS:
        raise KeyError(f"unknown preset {preset!r}; have {sorted(PRESETS)}")
    cfg = PRESETS[preset]()
    return cfg.override(**overrides) if overrides else cfg
