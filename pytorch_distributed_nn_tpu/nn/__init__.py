"""NN utilities shared by the model zoo.

The reference's models are ordinary ``torch.nn.Module`` subclasses
(SURVEY.md §2a Models row). Here models are flax.linen modules — the
idiomatic JAX compute path — and this package holds the cross-cutting
pieces: the mixed-precision dtype policy (bf16 compute / f32 params, the
TPU-native analogue of CUDA amp) and rematerialisation helpers.
"""

from pytorch_distributed_nn_tpu.nn.dtypes import Policy, get_policy

__all__ = ["Policy", "get_policy"]
