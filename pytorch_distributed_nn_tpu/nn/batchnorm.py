"""TPU BatchNorm with controllable statistics lowering.

Round-4's ResNet-50 trace blamed "BN statistic reductions" for 50% of
the step, prescribing a fused stats kernel (VERDICT r4 Next #1). The
round-5 HLO inventory of the compiled step (scripts/resnet_hlo.py)
showed the premise was inverted: XLA:TPU *already* fuses the BN sums
into the convolutions — every fwd conv lowers to a
``convert_reduce_fusion`` emitting (Σx, Σx², conv_out) in one pass, and
most bwd-data convs carry the (Σdy, Σdy·x̂) epilogue the same way. The
23.4 ms trace bucket attributed to "BN statistics" is really *convs
slowed down by their reduction epilogues*: the compiler's own cost model
prices the fused conv+reduce at ~2.4x a clean conv (24.7M estimated
cycles for the 54 fwd conv+stats fusions vs ~10M for the equivalent
bare convs).

So the tunable worth having is the opposite of the prescribed one:
**keep the stats OUT of the conv** (optimization_barrier fences), pay
explicit HBM passes for the reductions, and run the convs at full MXU
speed. This module provides both lowerings behind one flax interface so
the choice is a measured A/B, not a theory:

- ``stats_impl='fused'``  — plain jnp formulas; XLA fuses stats into
  the producing conv (today's default behavior, for baseline parity).
- ``stats_impl='unfused'`` — closed-form custom_vjp with
  ``optimization_barrier`` around x (fwd) and dy (bwd): stats and
  normalize become standalone passes, convs lower clean.
- ``stats_impl='pallas'`` — like 'unfused', but the two reduction
  passes (fwd Σx/Σx², bwd Σdy/Σdy·x) run as Pallas kernels
  (ops/pallas/bn_stats.py) tiled for streaming HBM bandwidth; jnp
  fallback off-TPU keeps CPU tests exact.

Semantics match ``flax.linen.BatchNorm`` (feature axis -1, f32 stats,
biased batch variance in the running stats, momentum EMA); the oracle
test is tests/test_batchnorm.py. Under compiler-sharded DP the
fused/unfused impls keep flax's SyncBN behavior (the jnp reductions
span the global batch — psum inserted by the partitioner). The pallas
impl targets the single-chip/shard_map regime; use 'unfused' on a
multi-chip compiler-sharded mesh (pallas_call has no SPMD partitioning
rule).

Reference parity note: torch DDP BatchNorm normalizes with *local*
per-process stats (SyncBN is opt-in there); flax-style global-batch
stats are strictly stronger. See models/resnet.py.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import linen as nn

from pytorch_distributed_nn_tpu.ops.pallas.bn_stats import (
    sum_and_sumsq,
    sum_and_dot,
)

_IMPLS = ("fused", "unfused", "pallas", "unfused_fwd", "unfused_bwd")


def _reduce_axes(ndim: int) -> tuple[int, ...]:
    return tuple(range(ndim - 1))


def _stats_fwd(x, impl: str):
    """(Σx, Σx²) over all leading axes, f32, one logical pass."""
    if impl == "pallas":
        return sum_and_sumsq(x)
    xf = x.astype(jnp.float32)
    axes = _reduce_axes(x.ndim)
    return jnp.sum(xf, axes), jnp.sum(xf * xf, axes)


def _sums_bwd(dy, x, impl: str):
    """(Σdy, Σdy·x) over all leading axes, f32, one logical pass."""
    if impl == "pallas":
        return sum_and_dot(dy, x)
    dyf = dy.astype(jnp.float32)
    xf = x.astype(jnp.float32)
    axes = _reduce_axes(x.ndim)
    return jnp.sum(dyf, axes), jnp.sum(dyf * xf, axes)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train(x, scale, bias, epsilon: float, impl: str):
    (y, mean, var), _res = _bn_train_fwd(x, scale, bias, epsilon, impl)
    return y, mean, var


def _bn_train_fwd(x, scale, bias, epsilon: float, impl: str):
    if impl in ("unfused", "pallas", "unfused_fwd"):
        # fence: keep the stat reductions OUT of the producing conv's
        # fusion, so the conv lowers clean and the stats become a
        # standalone streaming pass
        x = jax.lax.optimization_barrier(x)
    m = x.size // x.shape[-1]
    s1, s2 = _stats_fwd(x, impl)
    mean = s1 / m
    var = s2 / m - mean * mean
    rsig = jax.lax.rsqrt(var + epsilon)
    # elementwise pass in f32 (flax promotes bf16·f32 the same way);
    # converts fuse, the result lands back in x.dtype
    y = (x.astype(jnp.float32) * (rsig * scale)
         + (bias - mean * rsig * scale)).astype(x.dtype)
    return (y, mean, var), (x, scale, mean, rsig)


def _bn_train_bwd(epsilon: float, impl: str, res, cts):
    # cts[1]/cts[2] (batch mean/var cotangents) are intentionally
    # dropped: the stats feed the running-average EMA, a non-
    # differentiated state update (flax's batch_stats collection has the
    # same property — no gradient ever flows through it)
    x, scale, mean, rsig = res
    dy = cts[0]
    if impl in ("unfused", "pallas", "unfused_bwd"):
        dy = jax.lax.optimization_barrier(dy)
    m = x.size // x.shape[-1]
    sdy, sdyx = _sums_bwd(dy, x, impl)
    # Σdy·x̂ from the raw moments: x̂ = (x - μ)·rsig
    sdyxh = (sdyx - mean * sdy) * rsig
    dbias = sdy
    dscale = sdyxh
    # dx = γ·rsig·(dy − Σdy/m − x̂·Σdy·x̂/m); fold μ into the x
    # coefficient so the elementwise pass reads only x and dy:
    # x̂·Σdy·x̂/m = x·(rsig·Σdy·x̂/m) − μ·rsig·Σdy·x̂/m
    g = scale * rsig
    c2 = g * rsig * sdyxh / m
    c1 = g * sdy / m - mean * c2
    dx = (dy.astype(jnp.float32) * g - x.astype(jnp.float32) * c2
          - c1).astype(x.dtype)
    return dx, dscale, dbias


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


def batch_norm_train(x, scale, bias, *, epsilon: float = 1e-5,
                     impl: str = "unfused"):
    """Functional train-mode batch norm: returns (y, batch_mean,
    batch_var). Gradients flow through y only (the stats feed running-
    average updates, which are not differentiated — matching how
    flax.linen.BatchNorm's batch_stats are consumed)."""
    if impl not in _IMPLS:
        raise ValueError(f"unknown stats_impl {impl!r}; have {_IMPLS}")
    return _bn_train(x, scale, bias, epsilon, impl)


class TpuBatchNorm(nn.Module):
    """Drop-in for flax.linen.BatchNorm (feature axis -1) with the
    statistics-lowering control described in the module docstring."""

    use_running_average: bool | None = None
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = None
    param_dtype: Any = jnp.float32
    use_bias: bool = True
    use_scale: bool = True
    bias_init: Callable = nn.initializers.zeros
    scale_init: Callable = nn.initializers.ones
    stats_impl: str = "unfused"

    @nn.compact
    def __call__(self, x, use_running_average: bool | None = None):
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average,
            use_running_average)
        c = x.shape[-1]
        dtype = self.dtype or x.dtype
        x = x.astype(dtype)
        scale = (self.param("scale", self.scale_init, (c,),
                            self.param_dtype).astype(jnp.float32)
                 if self.use_scale else jnp.ones((c,), jnp.float32))
        bias = (self.param("bias", self.bias_init, (c,),
                           self.param_dtype).astype(jnp.float32)
                if self.use_bias else jnp.zeros((c,), jnp.float32))
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((c,), jnp.float32))
        if use_ra:
            rsig = jax.lax.rsqrt(ra_var.value + self.epsilon)
            return (x.astype(jnp.float32) * (rsig * scale)
                    + (bias - ra_mean.value * rsig * scale)).astype(dtype)
        y, mean, var = batch_norm_train(
            x, scale, bias, epsilon=self.epsilon, impl=self.stats_impl)
        if not self.is_initializing():
            ra_mean.value = (self.momentum * ra_mean.value
                             + (1.0 - self.momentum) * mean)
            ra_var.value = (self.momentum * ra_var.value
                            + (1.0 - self.momentum) * var)
        return y
