"""Weight-only int8 flax modules + the f32→int8 param converter.

The capacity path that puts the TRUE Llama-3-8B on one 16 GB v5e chip
(VERDICT r3 Missing #1): parameters are stored int8 with per-output-
channel f32 scales (~8 GB for 8.03 B params vs 16 GB bf16), and every
matmul dequantizes tile-wise in VMEM via the Pallas kernel
(ops/pallas/int8_matmul.py). Swap-in equivalents for the three linen
primitives the transformer families use:

- :class:`Int8Dense`         ↔ ``nn.Dense`` (no-bias)
- :class:`Int8DenseGeneral`  ↔ ``nn.DenseGeneral`` (tuple features
  and/or multi-axis inputs — attention q/k/v/out projections)
- :class:`Int8Embed`         ↔ ``nn.Embed`` (per-ROW scales: lookups
  are gathers, so rows — not output channels — are the quantization
  group)

Storage is pre-padded to the kernel's block multiples (``padded_kn``)
so the hot decode path never re-pads 8 GB of weights; padded rows/cols
hold zeros and drop out of the math. :func:`quantize_model_params`
converts a float param tree into this layout in one pass —
round-to-nearest symmetric, the standard weight-only recipe (tested
against the f32 oracle in tests/test_quantized.py).
"""

from __future__ import annotations

import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from pytorch_distributed_nn_tpu.ops.pallas.int8_matmul import (
    int8_matmul,
    padded_kn,
    quantize_weight,
)


def _int8_init(rng, shape, dtype=jnp.int8):
    """Self-init for synthetic-weight runs (zero-egress container: no
    real checkpoint to quantize). Uniform int8 in [-64, 64) keeps
    activations finite through 32 layers once multiplied by the
    fan-in-scaled ``scale`` init below."""
    return jax.random.randint(rng, shape, -64, 64, jnp.int8)


def _scale_init_for(fan_in: int):
    def init(rng, shape, dtype=jnp.float32):
        # dequantized weight std ≈ 64/sqrt(3) * s; match He-ish
        # 1/sqrt(fan_in) so synthetic forward passes stay O(1)
        return jnp.full(shape, 1.0 / (37.0 * math.sqrt(fan_in)), dtype)
    return init


class Int8Dense(nn.Module):
    """``nn.Dense(use_bias=False)`` with int8 kernel + per-out-channel
    scale. Param layout: ``kernel_q`` (Kp, Np) int8, ``scale`` (1, Np)
    f32 — padded storage (see module docstring)."""

    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        k = x.shape[-1]
        kp, np_ = padded_kn(k, self.features)
        q = self.param("kernel_q", _int8_init, (kp, np_))
        s = self.param("scale", _scale_init_for(k), (1, np_))
        lead = x.shape[:-1]
        y = int8_matmul(x.reshape(-1, k), q, s, out_dtype=self.dtype)
        return y[:, : self.features].reshape(*lead, self.features)


class Int8DenseGeneral(nn.Module):
    """``nn.DenseGeneral`` over trailing input axes with tuple
    features: internally always one padded 2-D matmul (prod(in_axes) →
    prod(features)), reshaped at the boundary."""

    features: Sequence[int] | int
    axis: Sequence[int] | int = -1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        feats = (self.features,) if isinstance(self.features, int) \
            else tuple(self.features)
        axes = (self.axis,) if isinstance(self.axis, int) \
            else tuple(self.axis)
        axes = tuple(a % x.ndim for a in axes)
        if axes != tuple(range(x.ndim - len(axes), x.ndim)):
            raise ValueError(
                f"Int8DenseGeneral needs trailing contraction axes, "
                f"got {axes} for ndim {x.ndim}"
            )
        k = math.prod(x.shape[a] for a in axes)
        n = math.prod(feats)
        kp, np_ = padded_kn(k, n)
        q = self.param("kernel_q", _int8_init, (kp, np_))
        s = self.param("scale", _scale_init_for(k), (1, np_))
        lead = x.shape[: x.ndim - len(axes)]
        y = int8_matmul(x.reshape(-1, k), q, s, out_dtype=self.dtype)
        return y[:, :n].reshape(*lead, *feats)


class Int8Embed(nn.Module):
    """``nn.Embed`` with int8 rows + per-row scales (a lookup reads one
    row, so the row is the dequant group — no padding needed)."""

    num_embeddings: int
    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, tokens):
        q = self.param("embedding_q", _int8_init,
                       (self.num_embeddings, self.features))
        s = self.param(
            "scale", _scale_init_for(1), (self.num_embeddings, 1))
        rows = jnp.take(q, tokens, axis=0).astype(self.dtype)
        return rows * jnp.take(s, tokens, axis=0).astype(self.dtype)


def synthetic_int8_params(model, sample_tokens, seed: int = 0) -> Any:
    """Random parameters for a QUANTIZED model at full size without
    ever materializing floats (zero-egress container: there is no real
    8B checkpoint to quantize; decode speed is value-independent).

    ``jax.eval_shape`` over ``model.init`` gives the structure; each
    leaf fills directly on device — int8 leaves uniform in [-64, 64)
    (matching :func:`_int8_init`), 2-D quant scales a fan-in-ish small
    constant, 1-D norm scales ones. One small dispatch per leaf instead
    of one init graph over the whole 8 GB model.
    """
    shapes = jax.eval_shape(
        lambda: model.init(
            jax.random.key(0), jnp.zeros_like(sample_tokens))
    )["params"]
    flat, treedef = jax.tree_util.tree_flatten_with_path(shapes)
    key = jax.random.key(seed)
    leaves = []
    for i, (path, s) in enumerate(flat):
        k = jax.random.fold_in(key, i)
        if s.dtype == jnp.int8:
            leaves.append(jax.random.randint(k, s.shape, -64, 64,
                                             jnp.int8))
        elif s.ndim >= 2:  # quant scale (1, Np) / (V, 1)
            leaves.append(jnp.full(s.shape, 2.7e-4, s.dtype))
        else:  # norm scales etc.
            leaves.append(jnp.ones(s.shape, s.dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _merge_fused_projections(params: dict, qparams_shapes: Any) -> dict:
    """Rewrite float {query,key,value} / {gate_proj,up_proj} subtrees
    into the fused-projection layout when the quantized target declares
    'qkv' / 'gate_up' modules (fused_proj=True, the default). EXACT:
    quantization scales are per-OUTPUT-channel, and concatenating
    kernels along the output axis leaves every channel's absmax — and
    therefore its scale and rounded int8 values — untouched, so
    quantize(concat) == concat(quantize)."""
    if not isinstance(qparams_shapes, dict):
        return params
    fused = dict(params)
    if ("qkv" in qparams_shapes and "qkv" not in fused
            and {"query", "key", "value"} <= fused.keys()):
        ks = [fused.pop(n)["kernel"] for n in ("query", "key", "value")]
        # DenseGeneral kernels: (d_model, heads, head_dim) — heads is
        # the concat axis of the fused (H + 2*Hkv, head_dim) features
        fused["qkv"] = {"kernel": jnp.concatenate(ks, axis=1)}
    if ("gate_up" in qparams_shapes and "gate_up" not in fused
            and {"gate_proj", "up_proj"} <= fused.keys()):
        ks = [fused.pop(n)["kernel"] for n in ("gate_proj", "up_proj")]
        fused["gate_up"] = {"kernel": jnp.concatenate(ks, axis=1)}
    return fused


def quantize_model_params(params: Any, qparams_shapes: Any) -> Any:
    """Convert a float flax param tree to the int8 modules' layout.

    Rewrites, recursively: ``{'kernel': w}`` → ``{'kernel_q', 'scale'}``
    (per-output-channel, leading axes flattened into the contraction)
    and ``{'embedding': w}`` → ``{'embedding_q', 'scale'}`` (per-row);
    norm scales and biases pass through. ``qparams_shapes`` is the
    ``jax.eval_shape`` param tree of the QUANTIZED model — its
    ``kernel_q`` shapes resolve the >2-D DenseGeneral ambiguity (how
    many kernel axes are contraction vs features) that a shape-blind
    walk cannot. The result applies under the same module tree built
    with ``quantized=True`` — tests/test_quantized.py checks logit
    agreement against the float oracle.
    """
    if not isinstance(params, dict):
        return params
    params = _merge_fused_projections(params, qparams_shapes)
    out = {}
    for name, leaf in params.items():
        if name == "kernel" and hasattr(leaf, "shape"):
            tgt = qparams_shapes["kernel_q"]
            # split leaf axes into (in..., feat...) so prod(in) pads
            # to kp; distinct splits can pad to the same storage
            # (e.g. (16,·,64): both 16×128 and 32×64 pad to 32×128),
            # and reshaping on the wrong contraction boundary would
            # quantize silently wrong — so demand a UNIQUE match
            matches = []
            for split in range(1, leaf.ndim):
                k = math.prod(leaf.shape[:split])
                n = math.prod(leaf.shape[split:])
                if padded_kn(k, n) == tuple(tgt.shape) and \
                        (k, n) not in [(m[1], m[2]) for m in matches]:
                    matches.append((split, k, n))
            if not matches:
                raise ValueError(
                    f"no axis split of {leaf.shape} matches padded "
                    f"storage {tgt.shape}"
                )
            if len(matches) > 1:
                raise ValueError(
                    f"ambiguous axis split of {leaf.shape}: "
                    f"{[(m[1], m[2]) for m in matches]} all pad to "
                    f"{tuple(tgt.shape)}; quantize with unambiguous "
                    f"dims or pre-reshape the kernel to 2-D"
                )
            _, k, n = matches[0]
            q, s = quantize_weight(leaf.reshape(k, n))
            out["kernel_q"] = q
            out["scale"] = s
        elif name == "embedding" and hasattr(leaf, "shape"):
            w32 = leaf.astype(jnp.float32)
            absmax = jnp.max(jnp.abs(w32), axis=1, keepdims=True)
            s = jnp.where(absmax > 0, absmax / 127.0, 1.0)
            out["embedding_q"] = jnp.clip(
                jnp.round(w32 / s), -127, 127).astype(jnp.int8)
            out["scale"] = s.astype(jnp.float32)
        elif isinstance(leaf, dict):
            out[name] = quantize_model_params(
                leaf, qparams_shapes[name])
        else:
            out[name] = leaf
    return out
