"""Mixed-precision policy: params in f32, compute in bf16.

On TPU the MXU natively multiplies bf16 with f32 accumulation, so "amp"
is just a dtype choice on the module — no loss scaling needed (bf16 has
f32's exponent range, unlike fp16 on the reference's GPUs).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
}


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: jnp.dtype
    compute_dtype: jnp.dtype

    def cast_to_compute(self, tree):
        import jax

        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if hasattr(x, "astype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            tree,
        )


def get_policy(param_dtype: str = "float32",
               compute_dtype: str = "bfloat16") -> Policy:
    try:
        return Policy(_DTYPES[param_dtype], _DTYPES[compute_dtype])
    except KeyError as e:
        raise ValueError(
            f"unknown dtype {e.args[0]!r}; have {sorted(_DTYPES)}"
        ) from None
