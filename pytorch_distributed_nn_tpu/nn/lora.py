"""Per-request LoRA adapters for multi-tenant serving.

One resident base model serves many fine-tunes: each adapter is a pair
of rank-``r`` factors per layer for the attention q/v projections
(the classic LoRA placement), stored STACKED across adapters so the
whole bank is four arrays and per-request selection is one gather —
``a_q[ids]`` — inside the jitted forward, not a params swap. A batch
row's delta is ``(x @ A) @ B`` added to the projection output before
rotary, so rows with different adapters coexist in one decode batch
(the serving engine keys the gather on a per-slot adapter-id mirror).

Bank layout (``num_adapters`` leading, layer axis second)::

    a_q: (n, L, d_model, r)      b_q: (n, L, r, H,   head_dim)
    a_v: (n, L, d_model, r)      b_v: (n, L, r, Hkv, head_dim)

Adapter 0 is the **base model**: its B factors are zeros, so its delta
is exactly ``x @ A @ 0 == 0`` and a request that selects no adapter
adds structural zeros — greedy output is token-identical to running
without a bank (tests/test_serve.py). Real deployments load trained
factors into this layout; :func:`init_lora_bank` mints a bank with
random small deltas for adapters >= 1 (bench / test traffic) and
:func:`merge_lora` folds one adapter into the base params — the
offline oracle that the dynamic gather path must match.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def lora_delta(x, a, b):
    """x: (B, T, d); a: (B, d, r); b: (B, r, H, Dh) -> (B, T, H, Dh).

    Two small matmuls with f32 accumulation (matching the projection
    einsums' ``preferred_element_type`` discipline); the result is cast
    back to x.dtype by the caller's add."""
    h = jnp.einsum("btd,bdr->btr", x, a,
                   preferred_element_type=jnp.float32)
    return jnp.einsum("btr,brhk->bthk", h, b,
                      preferred_element_type=jnp.float32).astype(x.dtype)


def init_lora_bank(model, *, num_adapters: int, rank: int, rng=None,
                   scale: float = 0.02):
    """Mint a stacked adapter bank shaped for ``model`` (Llama family:
    needs num_layers / d_model / num_heads / num_kv_heads attributes).

    Adapter 0's B factors are zeros (the base model); adapters >= 1 get
    N(0, scale) factors in both A and B — distinguishable outputs for
    bench traffic and routing tests. Trained fine-tunes overwrite the
    per-adapter slices."""
    if num_adapters < 1:
        raise ValueError(
            f"num_adapters must be >= 1, got {num_adapters}")
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    L = model.num_layers
    d = model.d_model
    H = model.num_heads
    Hkv = model.num_kv_heads or H
    Dh = d // H
    rng = rng if rng is not None else jax.random.key(0)
    ks = jax.random.split(rng, 4)
    n, r = num_adapters, rank

    def factor(k, shape):
        return (scale * jax.random.normal(k, shape)).astype(jnp.float32)

    bank = dict(
        a_q=factor(ks[0], (n, L, d, r)),
        b_q=factor(ks[1], (n, L, r, H, Dh)),
        a_v=factor(ks[2], (n, L, d, r)),
        b_v=factor(ks[3], (n, L, r, Hkv, Dh)),
    )
    # adapter 0 = base model: zero B => delta is exactly zero
    bank["b_q"] = bank["b_q"].at[0].set(0.0)
    bank["b_v"] = bank["b_v"].at[0].set(0.0)
    return bank


def num_adapters(bank) -> int:
    return 0 if bank is None else int(np.shape(bank["a_q"])[0])


def layer_slice(bank, layer: int):
    """The per-layer factor tuple the attention module consumes:
    ``(a_q, b_q, a_v, b_v)`` each with the layer axis removed."""
    return tuple(bank[k][:, layer]
                 for k in ("a_q", "b_q", "a_v", "b_v"))


def merge_lora(params, bank, adapter: int):
    """Fold one adapter's deltas into a COPY of the base params
    (Llama param naming: ``layer{i}/attn/{query,value}/kernel``).
    The oracle for the dynamic path: generate() with merged params
    must match the serving engine running adapter ``adapter``."""
    n = num_adapters(bank)
    if not 0 <= adapter < n:
        raise ValueError(f"adapter must be in [0, {n}), got {adapter}")
    merged = jax.tree.map(lambda x: x, params)
    L = np.shape(bank["a_q"])[1]
    for i in range(L):
        attn = dict(merged[f"layer{i}"]["attn"])
        for proj, ak, bk in (("query", "a_q", "b_q"),
                             ("value", "a_v", "b_v")):
            a = bank[ak][adapter, i]  # (d, r)
            b = bank[bk][adapter, i]  # (r, H, Dh)
            delta = jnp.einsum("dr,rhk->dhk", a, b,
                               preferred_element_type=jnp.float32)
            kern = attn[proj]["kernel"]
            attn[proj] = dict(attn[proj],
                              kernel=(kern + delta.astype(kern.dtype)))
        layer = dict(merged[f"layer{i}"], attn=attn)
        merged = dict(merged)
        merged[f"layer{i}"] = layer
    return merged
