"""Multi-head attention shared by the transformer families.

One module covers BERT (bidirectional), Transformer-LM (causal), and
Llama (causal + rotary + grouped-query). The inner product is routed
through :func:`dot_product_attention`, which selects the implementation:
``xla`` (einsum softmax — XLA fuses this well for moderate sequence
lengths) or ``flash`` (the Pallas blockwise kernel, ops/pallas/) once the
sequence is long enough to be HBM-bound. Ring/context-parallel attention
wraps the same kernel over the ``seq`` mesh axis (parallel/sequence.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from pytorch_distributed_nn_tpu.nn.quantized import Int8DenseGeneral


def rotary_embedding(q, k, *, theta: float = 10000.0, positions=None):
    """Apply rotary position embeddings to q, k of shape (B, T, H, D)."""
    d = q.shape[-1]
    if d % 2:
        raise ValueError(f"rotary needs even head_dim, got {d}")
    if positions is None:
        positions = jnp.arange(q.shape[1])[None, :]  # (1, T)
    freqs = theta ** (-jnp.arange(0, d // 2) * 2.0 / d)  # (D/2,)
    angles = positions[..., None] * freqs  # (B?, T, D/2)
    cos = jnp.cos(angles)[:, :, None, :]  # (B?, T, 1, D/2)
    sin = jnp.sin(angles)[:, :, None, :]

    def rotate(x):
        x1, x2 = jnp.split(x, 2, axis=-1)
        return jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
        )

    return rotate(q), rotate(k)


def _auto_impl(q_shape, k_shape, *, has_mask: bool,
               device_count: Optional[int] = None) -> str:
    """The 'auto' flash-vs-xla decision (see dot_product_attention's
    docstring for the v5e measurements behind the thresholds).

    ``device_count=None`` assumes the shapes are GLOBAL (jit/GSPMD
    trace-time shapes) and divides the B*H rows by ``jax.device_count()``
    for the fully-sharded worst case. Callers inside ``shard_map`` see
    per-device SHARD shapes and must pass ``device_count=1`` — otherwise
    the rows are divided twice and the T in [1024, 2048) flash upgrade
    never fires (advisor r3 finding)."""
    T = q_shape[1]
    if device_count is None:
        device_count = jax.device_count()
    rows_per_chip = (q_shape[0] * q_shape[2]) // max(device_count, 1)
    return ("flash" if jax.default_backend() == "tpu"
            and not has_mask and k_shape[1] == T
            and (T >= 2048 or (T >= 1024 and rows_per_chip >= 64))
            else "xla")


def dot_product_attention(
    q, k, v, *, causal: bool, impl: str = "xla",
    mask: Optional[jax.Array] = None,
    device_count: Optional[int] = None,
):
    """q: (B, T, H, D); k/v: (B, S, Hkv, D) with H % Hkv == 0.

    Returns (B, T, H, D). f32 softmax accumulation regardless of input
    dtype (MXU-friendly: bf16 operands, f32 accumulate).

    impl: 'xla' (fused by the compiler; required for padding masks and
    cross-length kv), 'flash' (Pallas kernels in both directions: the
    streamed forward plus the two-pass lse-replay backward), or 'auto'.
    Measured on v5e (llama-shaped blocks, fwd+bwd): xla wins at T=512;
    T=1k is an OCCUPANCY question — the flash grid parallelizes over
    B*H row-programs, and with too few the chip idles (batch-1
    full-model bench favors xla; batch-4 favors flash 1.2x; batch-16
    favors flash 1.41x, r3 A/B). Flash clearly wins from 2k up at any
    batch (1.33x+ with 1024-token blocks, growing with T — xla's
    (T, T) scores thrash HBM from 8k). So 'auto' picks flash on TPU
    for self-attention with no padding mask at T >= 2048, or at
    T >= 1024 with >= 64 B*H rows PER CHIP (the measured break-even).
    Trace-time shapes are GLOBAL under jit/GSPMD, so the per-chip rows
    divide the worst case — batch and heads fully sharded — by the
    device count; single-chip runs are unchanged, and a pod DP run at
    per-chip batch 1 correctly stays on xla.
    """
    if impl == "auto":
        impl = _auto_impl(q.shape, k.shape, has_mask=mask is not None,
                          device_count=device_count)
    if impl not in ("xla", "flash"):
        raise ValueError(f"unknown attention impl {impl!r}")
    B, T, H, D = q.shape
    S, Hkv = k.shape[1], k.shape[2]
    if impl == "flash":
        if mask is not None:
            raise ValueError(
                "flash impl does not take a padding mask; use impl='xla'"
            )
        from pytorch_distributed_nn_tpu.ops.pallas.flash_attention import (
            flash_attention,
        )

        # kv stays grouped: the kernel streams each KV tile for its
        # whole Q-head group (expanding here would multiply KV HBM
        # traffic by H/Hkv)
        return flash_attention(q, k, v, causal=causal)
    if H != Hkv:  # grouped-query: repeat kv heads for the einsum path
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    scale = D ** -0.5
    logits = jnp.einsum(
        "bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        causal_mask = jnp.tril(jnp.ones((T, S), dtype=bool))
        logits = jnp.where(causal_mask[None, None], logits, -1e30)
    if mask is not None:
        if mask.ndim == 2:  # (B, S) padding mask
            logits = jnp.where(mask[:, None, None, :], logits, -1e30)
        else:  # (B|1, T, S) position mask (decode: causal-by-index)
            logits = jnp.where(mask[:, None, :, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _row_update(buf, new, starts):
    """Per-row cache write: row ``i`` of ``new`` (T leading tokens)
    lands at ``buf[i, starts[i]:starts[i]+T]``. The continuous-batching
    primitive — each sequence in the batch advances at its own index
    instead of the shared scalar ``cache_index``. vmap over the batch
    dim keeps it one fused scatter, no host loop."""
    return jax.vmap(
        lambda b, n, s: jax.lax.dynamic_update_slice(
            b, n, (s,) + (0,) * (b.ndim - 1))
    )(buf, new, starts)


def _quantize_kv(x):
    """(B, T, H, D) → int8 values + (B, T, H) f32 scales: symmetric
    per-(token, head) absmax over the head dim. Zero rows (e.g. a
    dead head) get scale 1 so the stored zeros round-trip exactly."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127,
                 127).astype(jnp.int8)
    return q, scale


def _cache_attention(q, k, v, pos_mask, dtype, kscale=None, vscale=None):
    """Decode attention over the KV cache with GQA kept GROUPED: q
    reshapes to (B, T, Hkv, G, D) instead of repeating the cached K/V.
    (The einsum-path `jnp.repeat` materializes H/Hkv copies of the
    whole cache every step — at the 8B's b=128/S=256 that is ~17 GB of
    extra HBM traffic per decoded token; removing it is worth 3x+ on
    large-batch decode, measured r5, BASELINE.md decode table.)

    With ``kscale``/``vscale`` (both (B, S, Hkv) f32) the cache is the
    int8 layout and is never dequantized into a materialized copy:
    per-(token, head) scales commute with the two contractions — K's
    scale multiplies the logits AFTER QK^T (each logit is linear in
    one cached K row), V's scale multiplies the softmax probabilities
    BEFORE PV (the output is linear in each cached V row). The int8
    payloads go straight into the matmuls as raw integers (exact in
    bf16: |v| ≤ 127) and the f32 scales touch only the (…, S) score
    plane.

    q: (B, T, H, D); k/v: (B, S, Hkv, D) float — or int8 when the
    scales are given; pos_mask: (B|1, T, S). Returns (B, T, H, D)."""
    B, T, H, D = q.shape
    q5 = q.reshape(B, T, k.shape[2], H // k.shape[2], D)
    logits = jnp.einsum("btkgd,bskd->bkgts", q5, k.astype(dtype),
                        preferred_element_type=jnp.float32)
    logits *= D ** -0.5
    if kscale is not None:
        logits *= kscale.transpose(0, 2, 1)[:, :, None, None, :]
    logits = jnp.where(pos_mask[:, None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    if vscale is not None:
        probs = probs * vscale.transpose(0, 2, 1)[:, :, None, None, :]
    out = jnp.einsum("bkgts,bskd->btkgd", probs.astype(dtype),
                     v.astype(dtype),
                     preferred_element_type=jnp.float32)
    return out.reshape(B, T, H, D).astype(dtype)


class MultiHeadAttention(nn.Module):
    num_heads: int
    head_dim: int
    num_kv_heads: Optional[int] = None  # None = MHA; < num_heads = GQA
    causal: bool = False
    rotary: bool = False
    rope_theta: float = 10000.0
    impl: str = "xla"
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    use_bias: bool = True
    # weight-only int8 projections (nn/quantized.py): q/k/v/out kernels
    # stored int8 + per-out-channel scales, dequantized tile-wise in
    # the Pallas matmul — the capacity mode that fits Llama-3-8B's
    # weights in one chip's HBM. Bias-free only (the Llama family).
    quantized: bool = False
    # decode KV-cache storage: "compute" (the activation dtype, bf16 in
    # the presets) or "int8" — per-(token, head) symmetric scales,
    # halving cache HBM so the servable batch roughly doubles (the 8B
    # b=192 OOM edge). The int8 path never materializes a dequantized
    # cache: K's scale folds into the logits AFTER the QK^T contraction
    # and V's scale folds into the probabilities BEFORE the PV one —
    # algebraically exact, oracle-tested in tests/test_kv_cache.py.
    cache_dtype: str = "compute"
    # quantized path only: compute q/k/v in ONE int8 matmul over a
    # (H + 2*Hkv, head_dim) fused kernel instead of three. Exact for
    # per-output-channel scales (quantize(concat) == concat(quantize) —
    # each output channel's absmax is untouched by the concat), and at
    # decode batch 1 the step is per-op-launch bound (~0.3 ms/layer of
    # fixed cost vs ~0.27 ms of weight streaming), so fewer launches is
    # latency. quantize_model_params merges float q/k/v kernels into
    # the fused layout.
    fused_qkv: bool = False

    @nn.compact
    def __call__(self, x, mask: Optional[jax.Array] = None,
                 decode: bool = False,
                 cache_positions: Optional[jax.Array] = None,
                 lora=None):
        """``decode=True`` enables the autoregressive KV cache (flax
        "cache" collection): initialize by calling ``model.init`` with a
        (B, max_len) input and ``decode=True`` — that sizes the cache —
        then apply with ``mutable=["cache"]`` feeding (B, 1) (or a
        (B, P) prefill chunk); keys/values land at ``cache_index``,
        rotary positions are absolute, and attention masks to the
        filled prefix. Causal-only (the cache is a running prefix).

        ``cache_positions`` (B,) int32 switches decode to *per-row*
        cache indexing: row ``i``'s fed tokens write at slot
        ``cache_positions[i]`` (its own filled length), rotary positions
        and the causal-by-index mask follow per row, and the shared
        scalar ``cache_index`` is neither read nor advanced. This is
        what lets a continuous-batching engine hold sequences at
        different decode depths in ONE batched cache (serve/engine.py)
        and what batched ragged-prompt generation reduces to
        (inference/generate.py ``prompt_lengths``). Each row's
        computation is exactly the shared-index computation for that
        row, so greedy decode stays token-identical to the sequential
        path.

        ``lora`` — per-row LoRA deltas for multi-tenant serving
        (nn/lora.py): a ``(a_q, b_q, a_v, b_v)`` tuple of per-BATCH-row
        factors (each leading dim B, already gathered from the stacked
        adapter bank by the caller). The deltas land on the q/v
        projection *outputs* before rotary and before any cache write,
        so cached KV rows embed the adapter's deltas — which is why the
        prefix cache namespaces its content addresses by adapter id. A
        zero-B adapter contributes an exact-0.0 delta: adding it leaves
        greedy decode token-identical to running without a bank."""
        kv_heads = self.num_kv_heads or self.num_heads
        if self.quantized:
            if self.use_bias:
                raise ValueError("quantized attention is bias-free")
            dense = lambda heads, name: Int8DenseGeneral(  # noqa: E731
                (heads, self.head_dim), axis=-1, name=name,
                dtype=self.dtype,
            )
        else:
            dense = lambda heads, name: nn.DenseGeneral(  # noqa: E731
                (heads, self.head_dim), axis=-1, name=name,
                dtype=self.dtype, param_dtype=self.param_dtype,
                use_bias=self.use_bias,
            )
        if self.quantized and self.fused_qkv:
            h = self.num_heads
            qkv = dense(h + 2 * kv_heads, "qkv")(x)
            q = qkv[..., :h, :]
            k = qkv[..., h:h + kv_heads, :]
            v = qkv[..., h + kv_heads:, :]
        else:
            q = dense(self.num_heads, "query")(x)
            k = dense(kv_heads, "key")(x)
            v = dense(kv_heads, "value")(x)
        if lora is not None:
            from pytorch_distributed_nn_tpu.nn.lora import lora_delta

            a_q, b_q, a_v, b_v = lora
            q = q + lora_delta(x, a_q, b_q)
            v = v + lora_delta(x, a_v, b_v)
        if decode and not self.causal:
            raise ValueError("decode cache requires causal attention")
        if decode and mask is not None:
            raise ValueError(
                "decode mode ignores padding masks; strip padding (or "
                "left-trim) before prefill"
            )
        if cache_positions is not None and not decode:
            raise ValueError(
                "cache_positions is a decode-cache feature (per-row "
                "cache indices); it needs decode=True"
            )
        if self.impl in ("ring", "ulysses"):
            # Sequence/context parallelism at the model level: the
            # activation's T dim is sharded over the `seq` mesh axis and
            # attention runs inside a nested shard_map (seq manual,
            # other mesh axes stay auto) — either as a KV ring or as
            # Ulysses all-to-all head-scatter (parallel/sequence.py).
            # Requires an ambient mesh (Trainer sets it when mesh.seq >
            # 1) and causal attention; rotary positions are global
            # (computed from the shard's ring index) and applied before
            # any resharding, so both schemes see identical q/k.
            if decode:
                raise ValueError(
                    f"{self.impl} attention has no decode cache; "
                    "generate with impl='auto'"
                )
            if not self.causal or mask is not None:
                raise ValueError(
                    f"{self.impl} attention is causal-only and takes "
                    "no mask"
                )
            from jax.sharding import PartitionSpec as _P

            from pytorch_distributed_nn_tpu.parallel.sequence import (
                ring_attention,
                ulysses_attention,
            )
            from pytorch_distributed_nn_tpu.runtime.mesh import AXIS_SEQ

            seq_impl = self.impl

            def attn_local(q, k, v):
                if self.rotary:
                    Tl = q.shape[1]
                    start = jax.lax.axis_index(AXIS_SEQ) * Tl
                    pos = start + jnp.arange(Tl)[None]
                    q, k = rotary_embedding(q, k, theta=self.rope_theta,
                                            positions=pos)
                    q = q.astype(self.dtype)
                    k = k.astype(self.dtype)
                if seq_impl == "ulysses":
                    return ulysses_attention(q, k, v, axis=AXIS_SEQ,
                                             causal=True)
                return ring_attention(q, k, v, axis=AXIS_SEQ,
                                      causal=True)

            # axis_names: manual over seq ONLY — without it shard_map
            # goes manual over every mesh axis and the unsharded specs
            # all-gather the batch dim over data x fsdp, silently
            # negating data parallelism at every attention layer
            # (check_vma stays on: check_vma=False combined with
            # axis_names flips every mesh axis manual and the specs
            # get rejected; ring carries are pvary'd instead)
            out = jax.shard_map(
                attn_local,
                in_specs=(_P(None, AXIS_SEQ),) * 3,
                out_specs=_P(None, AXIS_SEQ),
                axis_names={AXIS_SEQ},
            )(q, k, v)
        elif decode:
            B, T = x.shape[0], x.shape[1]
            if self.cache_dtype not in ("compute", "int8"):
                raise ValueError(
                    f"unknown cache_dtype {self.cache_dtype!r}; have "
                    "('compute', 'int8')"
                )
            int8_cache = self.cache_dtype == "int8"
            init_k = nn.initializers.zeros
            kv_shape = (B, T, kv_heads, self.head_dim)
            cached_k = self.variable(
                "cache", "cached_key", init_k, None, kv_shape,
                jnp.int8 if int8_cache else k.dtype,
            )
            cached_v = self.variable(
                "cache", "cached_value", init_k, None, kv_shape,
                jnp.int8 if int8_cache else v.dtype,
            )
            if int8_cache:
                k_scale = self.variable(
                    "cache", "cached_key_scale", init_k, None,
                    (B, T, kv_heads), jnp.float32,
                )
                v_scale = self.variable(
                    "cache", "cached_value_scale", init_k, None,
                    (B, T, kv_heads), jnp.float32,
                )
            cache_index = self.variable(
                "cache", "cache_index",
                lambda: jnp.zeros((), jnp.int32),
            )
            if self.is_initializing():
                # init only sizes the cache from the (B, max_len) input;
                # the out-projection just needs a correctly-shaped
                # activation, so skip the attention math entirely
                out = jnp.zeros_like(q)
            else:
                S = cached_k.value.shape[1]
                if cache_positions is None:
                    idx = cache_index.value
                    positions = idx + jnp.arange(T)[None]  # absolute
                    cache_index.value = idx + T

                    def write(buf, new):
                        return jax.lax.dynamic_update_slice(
                            buf, new, (0, idx) + (0,) * (buf.ndim - 2))
                else:
                    # per-row mode: each sequence advances at its own
                    # index; the shared counter stays untouched (it is
                    # meaningless across rows at different depths)
                    starts = cache_positions.astype(jnp.int32)
                    positions = starts[:, None] + jnp.arange(T)[None]

                    def write(buf, new):
                        return _row_update(buf, new, starts)
                if self.rotary:
                    q, k = rotary_embedding(q, k, theta=self.rope_theta,
                                            positions=positions)
                    q, k = q.astype(self.dtype), k.astype(self.dtype)
                # attend to the filled prefix: k_pos <= this row's q_pos
                # (per-row rows are left-aligned, so slot == position)
                k_pos = jnp.arange(S)[None, None, :]
                q_pos = positions[:, :, None]
                pos_mask = k_pos <= q_pos  # (B|1, T, S)
                if int8_cache:
                    kq_new, ks_new = _quantize_kv(k)
                    vq_new, vs_new = _quantize_kv(v)
                    cached_k.value = write(cached_k.value, kq_new)
                    cached_v.value = write(cached_v.value, vq_new)
                    k_scale.value = write(k_scale.value, ks_new)
                    v_scale.value = write(v_scale.value, vs_new)
                    out = _cache_attention(
                        q, cached_k.value, cached_v.value, pos_mask,
                        self.dtype, kscale=k_scale.value,
                        vscale=v_scale.value,
                    )
                else:
                    cached_k.value = write(cached_k.value, k)
                    cached_v.value = write(cached_v.value, v)
                    out = _cache_attention(
                        q, cached_k.value, cached_v.value, pos_mask,
                        self.dtype,
                    )
        else:
            if self.rotary:
                q, k = rotary_embedding(q, k, theta=self.rope_theta)
                q, k = q.astype(self.dtype), k.astype(self.dtype)
            out = dot_product_attention(q, k, v, causal=self.causal,
                                        impl=self.impl, mask=mask)
        if self.quantized:
            return Int8DenseGeneral(
                x.shape[-1], axis=(-2, -1), name="out", dtype=self.dtype,
            )(out)
        return nn.DenseGeneral(
            x.shape[-1], axis=(-2, -1), name="out", dtype=self.dtype,
            param_dtype=self.param_dtype, use_bias=self.use_bias,
        )(out)
