"""Dump + analyze the TPU-compiled HLO of the resnet50_dp train step:
which fusions touch BN-statistics reductions, how many HBM passes over
the activations do they make, and what does that predict for the fused
BN kernel (VERDICT r4 Next #1 groundwork).

Usage: python scripts/resnet_hlo.py [--dump /tmp/resnet_step.hlo]
"""

from __future__ import annotations

import argparse
import re
import sys
from collections import defaultdict

sys.path.insert(0, ".")

import jax


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dump", default="")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--stem", default="s2d")
    ap.add_argument("--bn-impl", default="flax")
    args = ap.parse_args()

    from pytorch_distributed_nn_tpu.config import get_config
    from pytorch_distributed_nn_tpu.train.trainer import Trainer

    cfg = get_config("resnet50_dp")
    cfg.data.batch_size = args.batch
    cfg.model.extra = dict(stem=args.stem, bn_impl=args.bn_impl)
    cfg.log_every = 0
    trainer = Trainer(cfg)
    batch = trainer.loader.batch_at(0)
    lowered = jax.jit(trainer.step_fn.__wrapped__
                      if hasattr(trainer.step_fn, "__wrapped__")
                      else trainer.step_fn).lower(trainer.state, *batch)
    compiled = lowered.compile()
    txt = compiled.as_text()
    if args.dump:
        with open(args.dump, "w") as f:
            f.write(txt)
        print(f"dumped {len(txt)/1e6:.1f} MB to {args.dump}")

    # every fusion instruction line in the entry computation
    fusion_re = re.compile(
        r"^\s*(?:ROOT\s+)?%?([\w.-]+)\s*=\s*(\([^)]*\)|\S+)\s+fusion\(",
        re.M)
    # shapes like bf16[128,56,56,256]{...}
    shape_re = re.compile(r"(bf16|f32)\[([0-9,]+)\]")

    def nbytes(dt, dims):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        return n * (2 if dt == "bf16" else 4)

    per_kind = defaultdict(lambda: [0, 0])  # kind -> [count, approx bytes]
    bn_lines = []
    for m in fusion_re.finditer(txt):
        name, outshape = m.group(1), m.group(2)
        line = txt[m.start():txt.index("\n", m.start())]
        kind = re.sub(r"[.\d]+$", "", name)
        total = sum(nbytes(dt, dims)
                    for dt, dims in shape_re.findall(line))
        per_kind[kind][0] += 1
        per_kind[kind][1] += total
        if "reduce" in name:
            bn_lines.append(line.strip()[:240])

    print("\n=== fusion kinds (count, Σ shape bytes on the line) ===")
    for kind, (cnt, b) in sorted(per_kind.items(),
                                 key=lambda kv: -kv[1][1]):
        print(f"  {kind:40s} x{cnt:4d}  {b/1e9:8.2f} GB")
    print(f"\n=== reduce fusions ({len(bn_lines)}) ===")
    for ln in bn_lines[:80]:
        print("  ", ln)


if __name__ == "__main__":
    main()
