#!/usr/bin/env python
"""Validate Pallas kernels against their jnp oracles on the real TPU chip
(tests/ runs on CPU where the wrappers fall back, so this script is the
kernels' correctness gate; run it whenever a kernel changes)."""

import sys

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_nn_tpu.ops.pallas.flash_attention import (
    _attention_reference,
    flash_attention,
)
from pytorch_distributed_nn_tpu.ops.pallas.quantize import (
    dequantize_int8,
    quantize_int8,
)


def check_flash() -> bool:
    ok = True
    rng = np.random.RandomState(0)
    # (B, T, H, D, Hkv): last two cases exercise GQA-native KV streaming
    for (B, T, H, D, Hkv) in [(2, 512, 8, 128, 8), (1, 1024, 4, 64, 4),
                              (1, 1024, 8, 64, 2), (2, 512, 8, 128, 4)]:
        q = rng.randn(B, T, H, D).astype(np.float32) * 0.3
        k = rng.randn(B, T, Hkv, D).astype(np.float32) * 0.3
        v = rng.randn(B, T, Hkv, D).astype(np.float32)
        for causal in (True, False):
            got = np.asarray(flash_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                causal=causal))
            to_bh = lambda x: jnp.asarray(x).transpose(0, 2, 1, 3).reshape(
                B * H, T, D)  # noqa: E731
            expand = lambda x: jnp.repeat(  # noqa: E731
                jnp.asarray(x), H // Hkv, axis=2)
            want = np.asarray(_attention_reference(
                to_bh(q), to_bh(expand(k)), to_bh(expand(v)),
                causal=causal,
            )).reshape(B, H, T, D).transpose(0, 2, 1, 3)
            err = float(np.abs(got - want).max())
            line_ok = err < 2e-2
            ok &= line_ok
            print(f"flash B{B} T{T} H{H}/kv{Hkv} D{D} causal={causal}: "
                  f"max_err={err:.2e} {'OK' if line_ok else 'FAIL'}")
    return ok


def check_flash_grad() -> bool:
    """Gradients through the full custom_vjp path (Pallas forward + the
    Pallas two-pass lse-replay backward) vs autodiff of the dense
    reference. Shapes cover BOTH grid regimes: T=512 (single-block,
    nq=nk=1) and T=2048 (multi-block — the qi-indexed lse plane, the
    causal live/clamp index maps, and cross-block scratch accumulation
    only execute when nq, nk > 1, and that is the only regime 'auto'
    uses flash in). T=1152 forces block 128 (sole divisor), nq=9:
    the sublane-grouped lse/delta blocking (_stat_subl) gets a PARTIAL
    tail group (1 valid row of 8) — out-of-bounds stat blocks on dim -2
    only exist on the real chip, interpret mode can't catch them."""
    ok = True
    rng = np.random.RandomState(4)
    # Hkv < H covers the GQA backward: grouped dk/dv accumulated over
    # the head group inside the dkv kernel's inner grid dim
    for (B, T, H, D, Hkv) in [(2, 512, 4, 64, 4), (1, 2048, 4, 64, 4),
                              (1, 2048, 4, 64, 2), (1, 1152, 4, 64, 2)]:
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32)) * 0.3
        k = jnp.asarray(rng.randn(B, T, Hkv, D).astype(np.float32)) * 0.3
        v = jnp.asarray(rng.randn(B, T, Hkv, D).astype(np.float32))

        def to_bh(x):
            h = x.shape[2]
            return x.transpose(0, 2, 1, 3).reshape(B * h, T, D)

        for causal in (True, False):
            def f_flash(q, k, v):
                return (flash_attention(q, k, v, causal=causal)
                        .astype(jnp.float32).sum())

            def f_ref(q, k, v):
                k = jnp.repeat(k, H // Hkv, axis=2)
                v = jnp.repeat(v, H // Hkv, axis=2)
                return (_attention_reference(
                    to_bh(q), to_bh(k), to_bh(v), causal=causal,
                ).astype(jnp.float32).sum())

            got = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
            want = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
            for gg, ww, name in zip(got, want, ("dq", "dk", "dv")):
                err = float(jnp.abs(gg - ww).max())
                line_ok = err < 2e-2
                ok &= line_ok
                print(f"flash-grad T{T} {name} causal={causal}: "
                      f"max_err={err:.2e} {'OK' if line_ok else 'FAIL'}")
    return ok


def check_quantize() -> bool:
    rng = np.random.RandomState(1)
    x = rng.randn(8, 1024).astype(np.float32)
    scale = float(np.abs(x).max() / 127.0)
    acc = np.zeros_like(x)
    n = 32
    for seed in range(n):
        q = quantize_int8(jnp.asarray(x), scale, seed=seed)
        acc += np.asarray(dequantize_int8(q, scale))
    err = float(np.abs(acc / n - x).max())
    ok = err < 4 * scale
    print(f"int8 stochastic quantize: mean-err={err:.2e} "
          f"(scale {scale:.2e}) {'OK' if ok else 'FAIL'}")
    return ok


def check_int8_matmul() -> bool:
    """The weight-only int8 dequant matmul (ops/pallas/int8_matmul.py)
    vs its dequantized-f32 oracle — the kernel under the TRUE-8B decode
    path — at llama layer shapes plus padded-tail geometries."""
    from pytorch_distributed_nn_tpu.ops.pallas.int8_matmul import (
        int8_matmul,
        quantize_weight,
    )

    rng = np.random.RandomState(3)
    ok = True
    for (m, k, n) in [(16, 4096, 14336), (16, 4096, 1024),
                      (1024, 4096, 4096), (5, 48, 200)]:
        w = jnp.asarray(rng.randn(k, n).astype(np.float32) * 0.05)
        x = jnp.asarray(rng.randn(m, k).astype(np.float32))
        q, s = quantize_weight(w)
        got = int8_matmul(x, q, s, out_dtype=jnp.float32)[:, :n]
        ref = jnp.asarray(x, jnp.bfloat16).astype(jnp.float32) @ (
            q.astype(jnp.float32)[:k, :n] * s[:, :n])
        err = float(jnp.max(jnp.abs(got - ref))
                    / (float(jnp.max(jnp.abs(ref))) + 1e-9))
        line_ok = err < 2e-2
        ok &= line_ok
        print(f"int8-matmul ({m},{k},{n}): rel_err={err:.2e} "
              f"{'OK' if line_ok else 'FAIL'}")
    return ok


def check_ring_block() -> bool:
    """The fused ring-attention block kernel vs its jnp oracle: a chain of
    block updates with rotating offsets — exactly what one device runs
    over a ring pass — must match, including the causal clamp."""
    from pytorch_distributed_nn_tpu.ops.pallas.ring_attention import (
        STAT_LANES,
        _ring_block_pallas,
        _ring_block_reference,
    )

    ok = True
    rng = np.random.RandomState(2)
    BH, Tl, D, S = 8, 256, 128, 4  # 4-device ring, local seq 256
    q = jnp.asarray(rng.randn(BH, Tl, D).astype(np.float32) * 0.3)
    for causal in (True, False):
        for idx in range(S):  # device position in the ring
            m = jnp.full((BH, Tl, STAT_LANES), -1e30, jnp.float32)
            l = jnp.zeros((BH, Tl, STAT_LANES), jnp.float32)
            acc = jnp.zeros((BH, Tl, D), jnp.float32)
            m_r, l_r, acc_r = m, l, acc
            for i in range(S):  # ring steps: own block first
                src = (idx - i) % S
                k_blk = jnp.asarray(
                    rng.randn(BH, Tl, D).astype(np.float32) * 0.3)
                v_blk = jnp.asarray(
                    rng.randn(BH, Tl, D).astype(np.float32))
                offs = jnp.array([idx * Tl, src * Tl], jnp.int32)
                m, l, acc = _ring_block_pallas(
                    q, k_blk, v_blk, m, l, acc, offs, causal=causal,
                    block_q=128, block_k=128,
                    interpret=jax.default_backend() != "tpu")
                m_r, l_r, acc_r = _ring_block_reference(
                    q, k_blk, v_blk, m_r, l_r, acc_r, offs, causal=causal)
            got = np.asarray(acc / jnp.maximum(l[..., 0:1], 1e-30))
            want = np.asarray(acc_r / jnp.maximum(l_r[..., 0:1], 1e-30))
            err = float(np.abs(got - want).max())
            line_ok = err < 2e-2
            ok &= line_ok
            print(f"ring-block idx={idx}/{S} causal={causal}: "
                  f"max_err={err:.2e} {'OK' if line_ok else 'FAIL'}")
    return ok


def check_ring_bwd() -> bool:
    """The full fused ring path (Pallas forward + the per-step flash
    two-pass Pallas backward with lse replay) against autodiff of the
    dense reference, on a 1-device ring — the chip is single-device
    here, so this validates the kernels + custom_vjp plumbing on real
    hardware; the multi-device ring schedule (rotating dk/dv
    accumulators, causal flavor dispatch) is validated on the 8-device
    CPU interpret mesh by tests/test_sequence_parallel.py."""
    from pytorch_distributed_nn_tpu.parallel.sequence import (
        ring_attention,
    )
    from pytorch_distributed_nn_tpu.runtime.mesh import (
        MeshSpec,
        make_mesh,
    )
    from jax.sharding import PartitionSpec as P

    on_tpu = jax.default_backend() == "tpu"
    impl = "pallas" if on_tpu else "pallas_interpret"
    mesh = make_mesh(MeshSpec(seq=1, data=1))
    ok = True
    rng = np.random.RandomState(5)
    for (B, T, H, D, Hkv) in [(1, 1024, 4, 64, 4), (1, 1024, 4, 64, 2)]:
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(B, T, Hkv, D).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(B, T, Hkv, D).astype(np.float32))

        for causal in (True, False):
            def f_ring(q, k, v):
                def inner(a, b, c):
                    out = ring_attention(a, b, c, causal=causal,
                                         impl=impl)
                    return (out.astype(jnp.float32) ** 2).sum()

                mapped = jax.shard_map(
                    lambda a, b, c: jax.grad(
                        inner, argnums=(0, 1, 2))(a, b, c),
                    mesh=mesh, in_specs=(P(None, "seq"),) * 3,
                    out_specs=(P(None, "seq"),) * 3, check_vma=False,
                )
                return jax.jit(mapped)(q, k, v)

            def f_ref(q, k, v):
                kx = jnp.repeat(k, H // Hkv, axis=2)
                vx = jnp.repeat(v, H // Hkv, axis=2)
                to_bh = lambda x: x.transpose(0, 2, 1, 3).reshape(  # noqa: E731
                    B * H, T, D)
                out = _attention_reference(to_bh(q), to_bh(kx),
                                           to_bh(vx), causal=causal)
                return (out.astype(jnp.float32) ** 2).sum()

            got = f_ring(q, k, v)
            want = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
            for gg, ww, name in zip(got, want, ("dq", "dk", "dv")):
                err = float(jnp.abs(gg - ww).max())
                line_ok = err < 2e-2
                ok &= line_ok
                print(f"ring-bwd T{T} H{H}/kv{Hkv} {name} "
                      f"causal={causal}: max_err={err:.2e} "
                      f"{'OK' if line_ok else 'FAIL'}")
    return ok


def check_long_context() -> bool:
    """The streamed flash kernel at 128k-512k tokens on the REAL chip
    (SURVEY.md §5 long-context row names 32k-512k; the CPU harness
    can't execute these — T^2 on one host core trips XLA CPU's
    collective rendezvous deadline, see __graft_entry__, which instead
    AOT-compiles the 128k seq-sharded ring step). A dense oracle at
    128k would materialize a 68 GB score matrix, so correctness at
    these lengths rides the small-T oracle checks above; this check
    proves the kernel's real-TPU tiling/DMA/VMEM behavior AT LENGTH:
    fwd+bwd execute, outputs and grads finite, throughput printed."""
    import time

    on_tpu = jax.default_backend() == "tpu"
    ok = True
    rng = np.random.RandomState(6)
    lengths = [1 << 17, 1 << 19] if on_tpu else [1 << 12]
    for T in lengths:
        B, H, D, Hkv = 1, 4, 64, 2
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(B, T, Hkv, D).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(B, T, Hkv, D).astype(np.float32))

        def f(q, k, v):
            return (flash_attention(q, k, v, causal=True)
                    .astype(jnp.float32) ** 2).sum()

        grad_fn = jax.jit(jax.value_and_grad(f, argnums=(0, 1, 2)))
        (val, grads) = grad_fn(q, k, v)  # compile + warm
        # fence the warm-up through a host read: behind the axon
        # tunnel block_until_ready can return early (bench.py does the
        # same), which would start the timer mid-warm-up
        float(val)
        t0 = time.perf_counter()
        val, grads = grad_fn(q, k, v)
        jax.block_until_ready(grads)
        # fence through a host read (axon tunnel: block_until_ready can
        # return early — same workaround as bench.py)
        finite = bool(np.isfinite(float(val)))
        dt = time.perf_counter() - t0
        for g in grads:
            finite &= bool(jnp.isfinite(g).all())
        ok &= finite
        print(f"long-context flash fwd+bwd T={T}: {dt * 1e3:.1f} ms "
              f"({T / dt:.0f} tok/s) finite={finite} "
              f"{'OK' if finite else 'FAIL'}")
    return ok


def check_bn_stats() -> bool:
    """BatchNorm statistics kernels (ops/pallas/bn_stats.py) vs the jnp
    oracle, across the real ResNet channel geometries: C≥128 direct,
    C=64 lane-folded, and a (M % block)≠0 tail-masked case. The
    measured A/B keeps these OUT of the resnet50_dp default (XLA's
    conv+stats epilogue fusion wins — docs/design.md "ResNet-50 MFU"),
    but the kernels stay gated so the 'pallas' stats_impl stays
    correct."""
    from pytorch_distributed_nn_tpu.ops.pallas.bn_stats import (
        sum_and_dot,
        sum_and_sumsq,
    )

    ok = True
    rng = np.random.RandomState(11)
    # (N, H, W, C): C=64 exercises the fold, 7x7x512 the masked tail
    for shape in [(8, 14, 14, 256), (8, 28, 28, 64), (16, 7, 7, 512)]:
        x = jnp.asarray(rng.randn(*shape).astype(np.float32) * 2,
                        jnp.bfloat16)
        dy = jnp.asarray(rng.randn(*shape).astype(np.float32),
                         jnp.bfloat16)
        axes = tuple(range(x.ndim - 1))
        xf = np.asarray(x, np.float32)
        dyf = np.asarray(dy, np.float32)
        s1, s2 = jax.jit(sum_and_sumsq)(x)
        d1, d2 = jax.jit(sum_and_dot)(dy, x)
        good = True
        for got, want in [(s1, xf.sum(axes)), (s2, (xf * xf).sum(axes)),
                          (d1, dyf.sum(axes)), (d2, (dyf * xf).sum(axes))]:
            good &= bool(np.allclose(np.asarray(got), want, rtol=2e-3,
                                     atol=2e-2 * np.sqrt(xf.size)))
        ok &= good
        print(f"bn_stats {shape}: {'OK' if good else 'FAIL'}")
    return ok


def main() -> int:
    print(f"backend: {jax.default_backend()} devices: {jax.devices()}")
    if jax.default_backend() != "tpu":
        print("WARNING: not on TPU — validating fallbacks only")
    ok = (check_flash() & check_flash_grad() & check_quantize()
          & check_int8_matmul() & check_ring_block() & check_ring_bwd()
          & check_long_context() & check_bn_stats())
    print("ALL OK" if ok else "FAILURES")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
