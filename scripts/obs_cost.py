#!/usr/bin/env python
"""Abacus showback: the per-tenant chargeback report (obs/meter.py).

Reads the JSONL metrics stream a metered serving run wrote
(``TPUNN_METER=1`` + a ``metrics=`` sink: ``meter_ledger`` records,
one per tenant per summary flush — last-per-tenant wins, so a stream
with many flushes still renders the final ledgers) and prints the
showback table: per-tenant FLOPs, KV block-seconds, streamed wire
bytes, queue/decode wall time, tokens — and, under ``--price``, the
dollars each tenant owes plus their cost per 1k generated tokens.

The prefix-cache savings line is the counterfactual bill: FLOPs/tokens
the engine did NOT recompute because an admission rode a cached
prefix, credited to the tenant whose request skipped the work.

Every number is an integer straight off the meter's ledgers (the
per-tenant rows sum to the totals row EXACTLY — obs/meter.py's
integer-ledger contract), and the report JSON is canonical
(``sort_keys``): rendering the same ledgers twice is byte-identical.

Usage:
    python scripts/obs_cost.py runs/metrics.jsonl            # table
    python scripts/obs_cost.py runs/metrics.jsonl --json     # canonical
    python scripts/obs_cost.py runs/metrics.jsonl --price 2.0
    python scripts/obs_cost.py --selftest                    # tier-1 gate

``--price`` is dollars per PFLOP (1e15 FLOPs) billed — a deliberately
simple linear tariff; the analytic FLOP counts are the stable unit,
the tariff is policy.

The ``--selftest`` drill (the tier-1 acceptance gate, run as a
subprocess smoke by tests/test_quality.py) arms the meter, drives a
3-tenant mixed-prefix workload through a disaggregated fleet
(serve/disagg.py: every request crosses a prefill->decode handoff and
bills BOTH legs to its submitting tenant), and asserts the ledger
algebra: billed FLOPs reconcile with the analytic per-request counts
within 1%; per-tenant rows sum to the global totals exactly;
KV charges sum to the settle clock's wall witness exactly; the
rendered report is byte-identical across two renders.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, ".")  # run from repo root without install

from pytorch_distributed_nn_tpu.runtime.platform import (  # noqa: E402
    apply_platform_overrides,
)

apply_platform_overrides()

from pytorch_distributed_nn_tpu.obs.meter import (  # noqa: E402
    LEDGER_FIELDS,
    UNATTRIBUTED,
    ledger_totals,
)

PFLOP = 1e15


def load_events(path: str) -> list[dict]:
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # tolerate a torn tail line from a killed run
    return events


def ledgers_from_events(events: list[dict]) -> dict[str, dict[str, int]]:
    """Per-tenant ledgers from ``meter_ledger`` records, last-wins:
    the meter flushes cumulative ledgers at every summary boundary, so
    the newest record per tenant IS the final bill."""
    out: dict[str, dict[str, int]] = {}
    for e in events:
        if e.get("event") != "meter_ledger":
            continue
        tenant = str(e.get("tenant", UNATTRIBUTED))
        out[tenant] = {k: int(e.get(k, 0)) for k in LEDGER_FIELDS}
    return {t: out[t] for t in sorted(out)}


def build_report(ledgers: dict[str, dict[str, int]],
                 price_per_pflop: float = 0.0) -> dict:
    """The canonical report dict: per-tenant rows + exact totals +
    the savings credit, priced when a tariff is given. Pure in its
    inputs — same ledgers, same bytes (``to_json``)."""
    totals = ledger_totals(ledgers)
    report: dict = {"tenants": ledgers, "totals": totals}
    saved = {"tokens": totals["saved_tokens"],
             "flops": totals["saved_flops"]}
    if totals["flops"] + totals["saved_flops"] > 0:
        saved["billed_frac_avoided"] = round(
            totals["saved_flops"]
            / (totals["flops"] + totals["saved_flops"]), 6)
    report["savings"] = saved
    if price_per_pflop > 0:
        report["price_per_pflop"] = round(float(price_per_pflop), 6)
        cost = {}
        for tenant, led in ledgers.items():
            c = led["flops"] / PFLOP * price_per_pflop
            row = {"cost": round(c, 8)}
            if led["tokens"] > 0:
                row["cost_per_1k_tokens"] = round(
                    c * 1000.0 / led["tokens"], 8)
            cost[tenant] = row
        report["cost"] = cost
    return report


def to_json(report: dict) -> str:
    """Canonical bytes — the determinism unit the selftest asserts."""
    return json.dumps(report, sort_keys=True)


def render(report: dict) -> str:
    lines: list[str] = []
    out = lines.append
    tenants = report["tenants"]
    priced = "cost" in report
    out("== Abacus showback (obs/meter.py) ==")
    hdr = (f"{'tenant':>12} {'reqs':>5} {'tokens':>7} {'GFLOPs':>10} "
           f"{'kv_blk_s':>9} {'wire_MB':>8} {'queue_s':>8} "
           f"{'decode_s':>9}")
    if priced:
        hdr += f" {'$':>10} {'$/1k tok':>10}"
    out(hdr)
    rows = list(tenants.items()) + [("TOTAL", report["totals"])]
    for tenant, led in rows:
        row = (f"{tenant:>12} {led['requests']:>5} {led['tokens']:>7} "
               f"{led['flops'] / 1e9:>10.3f} "
               f"{led['kv_block_us'] / 1e6:>9.3f} "
               f"{led['wire_bytes'] / 1e6:>8.3f} "
               f"{led['queue_us'] / 1e6:>8.3f} "
               f"{led['decode_us'] / 1e6:>9.3f}")
        if priced:
            c = (report["cost"].get(tenant, {}) if tenant != "TOTAL"
                 else {"cost": round(sum(
                     r["cost"] for r in report["cost"].values()), 8)})
            row += f" {c.get('cost', 0.0):>10.6f}"
            row += (f" {c['cost_per_1k_tokens']:>10.6f}"
                    if "cost_per_1k_tokens" in c else f" {'-':>10}")
        out(row)
    s = report["savings"]
    out(f"prefix-cache savings: {s['tokens']} token(s) / "
        f"{s['flops'] / 1e9:.3f} GFLOPs not recomputed"
        + (f" ({s['billed_frac_avoided']:.1%} of the counterfactual "
           f"bill)" if "billed_frac_avoided" in s else ""))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# --selftest: the tier-1 acceptance drill
# ---------------------------------------------------------------------------

def _selftest() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    apply_platform_overrides()  # re-assert: setdefault above may be first
    import tempfile

    import numpy as np

    from pytorch_distributed_nn_tpu import obs
    from pytorch_distributed_nn_tpu.obs import flight, meter
    from pytorch_distributed_nn_tpu.utils.metrics import MetricsLogger

    flight.reset_recorder(enabled=True)
    obs.reset_registry()
    meter.reset()

    import jax
    import jax.numpy as jnp

    from pytorch_distributed_nn_tpu.config import ModelConfig
    from pytorch_distributed_nn_tpu.models import get_model
    from pytorch_distributed_nn_tpu.serve.disagg import DisaggFleet

    vocab = 97
    model = get_model(ModelConfig(
        name="llama3_8b", compute_dtype="float32", dtype="float32",
        extra=dict(num_layers=2, d_model=64, num_heads=4,
                   num_kv_heads=2, mlp_dim=128, vocab_size=vocab),
    ))
    params = model.init(jax.random.key(1),
                        jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]

    tenants = ("acme", "globex", "initech")
    rng = np.random.default_rng(7)
    base = rng.integers(1, vocab, size=(8,)).astype(np.int32)

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "metrics.jsonl")
        with MetricsLogger(path) as m:
            assert meter.maybe_init("1", metrics=m) is not None
            fleet = DisaggFleet(
                model, params, prefill=1, decode=2, max_slots=2,
                max_seq_len=64, block_size=4, max_queue=16, metrics=m)
            # mixed-prefix 3-tenant workload: evens share a warm
            # prefix (cache-savings path), odds are cold; every
            # request crosses the prefill->decode handoff
            tickets = []
            for i in range(6):
                tenant = tenants[i % 3]
                if i % 2 == 0:
                    tail = rng.integers(1, vocab,
                                        size=(4,)).astype(np.int32)
                    prompt = np.concatenate([base, tail])
                else:
                    prompt = rng.integers(
                        1, vocab, size=(6 + i,)).astype(np.int32)
                tickets.append(fleet.submit(prompt, 4, tenant=tenant))
                fleet.run_until_idle()  # serialize: warm prefixes land
            assert all(t.done.is_set() and t.ok for t in tickets), \
                "selftest workload did not complete"
            mi = meter.meter()
            # freeze the settle clock: cached-tier KV blocks outlive
            # the requests and keep accruing block-time, so the flush
            # below and the export afterwards must settle to the SAME
            # instant for the byte-identity check to be meaningful
            mi._clock = (lambda t=mi._clock(): t)
            summ = fleet.summary()  # flushes meter_ledger JSONL too
            assert "meter" in summ, "fleet summary lost the rollup"
            fpt = fleet.replicas[0].engine.flops_per_token()
            assert fpt > 0, "analytic cost model unavailable"

        ledgers = mi.export_ledgers()
        totals = ledger_totals(ledgers)

        # 1. per-tenant rows sum to the global totals EXACTLY
        for k in LEDGER_FIELDS:
            assert totals[k] == sum(led[k] for led in
                                    ledgers.values()), k

        # 2. disagg handoff attribution: both legs bill the submitting
        # tenant — nothing lands on "default", and every tenant paid
        assert "default" not in ledgers, ledgers.keys()
        for t in tenants:
            assert ledgers[t]["requests"] >= 2, (t, ledgers[t])
            assert ledgers[t]["flops"] > 0, (t, ledgers[t])

        # 3. FLOPs reconcile: round-boundary billing vs the analytic
        # per-request counts from the engines' serve_request records
        events = load_events(path)
        analytic = 0
        for e in events:
            if e.get("event") != "serve_request":
                continue
            prefilled = (int(e["prompt_len"])
                         - int(e.get("cached_tokens", 0)))
            analytic += (prefilled
                         + max(int(e["new_tokens"]) - 1, 0)) * fpt
        assert analytic > 0
        drift = abs(totals["flops"] - analytic) / analytic
        assert drift <= 0.01, (totals["flops"], analytic)

        # 4. refcount-weighted KV conservation: per-tenant block-us
        # charges sum to the settle clock's wall witness exactly
        assert totals["kv_block_us"] == mi._kv_wall_us, (
            totals["kv_block_us"], mi._kv_wall_us)

        # 5. the shared prefix actually produced a savings credit
        assert totals["saved_tokens"] > 0, "no prefix-cache credit"

        # 6. the JSONL feed round-trips to the same ledgers, and the
        # rendered report is byte-identical across two renders
        from_stream = ledgers_from_events(events)
        assert from_stream == ledgers, "meter_ledger stream drifted"
        r1 = to_json(build_report(from_stream, price_per_pflop=2.0))
        r2 = to_json(build_report(
            ledgers_from_events(load_events(path)),
            price_per_pflop=2.0))
        assert r1 == r2, "report is not deterministic"
        print(render(build_report(from_stream, price_per_pflop=2.0)))

    meter.reset()
    print("obs_cost selftest ok: "
          f"{len(ledgers)} tenant(s), {totals['flops']} FLOPs billed, "
          f"drift {drift:.5f}, {totals['saved_tokens']} token(s) saved")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("jsonl", nargs="?", default="",
                    help="metrics JSONL a metered run wrote "
                         "(meter_ledger records)")
    ap.add_argument("--price", type=float, default=0.0,
                    help="dollars per PFLOP billed (0 = unpriced)")
    ap.add_argument("--json", action="store_true",
                    help="print the canonical report JSON instead of "
                         "the table")
    ap.add_argument("--selftest", action="store_true",
                    help="run the 3-tenant disagg billing drill "
                         "(tier-1 acceptance gate)")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.jsonl:
        ap.error("need a metrics JSONL path (or --selftest)")
    try:
        events = load_events(args.jsonl)
    except OSError as e:
        print(f"cannot read {args.jsonl}: {e}", file=sys.stderr)
        return 1
    ledgers = ledgers_from_events(events)
    if not ledgers:
        # a quiet report, not a failure: the stream simply ran with
        # the meter unarmed (or hasn't flushed a summary yet)
        print(f"no meter_ledger records in {args.jsonl} "
              f"(run with TPUNN_METER=1 and a metrics sink)")
        return 0
    report = build_report(ledgers, price_per_pflop=args.price)
    print(to_json(report) if args.json else render(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
