#!/usr/bin/env python
"""Generation entrypoint: checkpoint -> KV-cache decode.

Usage:
    python scripts/generate.py --preset llama3_longcontext \
        [--checkpoint-dir runs/ckpt] [--prompt "5 17 42"] \
        [--max-new 32] [--temperature 0.8] [--top-k 40] [--seed 0] \
        [--tokenizer path/to/tokenizer_dir_or_json]

Prompts are space-separated token ids, or text when ``--tokenizer``
names a local HF tokenizer (a saved directory, or a tokenizer.json) —
the output is then detokenized too, and the tokenizer's eos stops
generation. Without --checkpoint-dir the model is randomly
initialized — useful only for smoke-testing the decode path.
"""

from __future__ import annotations

import argparse
import sys

sys.path.insert(0, ".")  # run from repo root without install

from pytorch_distributed_nn_tpu.runtime.platform import (
    apply_platform_overrides,
)

apply_platform_overrides()

import jax
import jax.numpy as jnp
import numpy as np


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", default="llama3_longcontext")
    ap.add_argument("--checkpoint-dir", default="")
    ap.add_argument("--prompt", default="1 2 3 4")
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--top-p", type=float, default=0.0,
                    help="nucleus sampling mass (0 = off)")
    ap.add_argument("--prefill-chunk", type=int, default=0,
                    help="consume the prompt in chunks of N tokens "
                         "(bounds prefill attention memory for long "
                         "prompts; 0 = one-shot)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--tokenizer", default="",
                    help="local HF tokenizer dir or tokenizer.json; "
                         "prompt/output become text")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree: decode SPMD over a "
                         "(tensor=tp, data=rest) mesh with sharded "
                         "params and KV cache")
    # remaining --a.b style flags are config overrides, as in train.py
    # (the model dims must match the checkpoint being decoded)
    args, rest = ap.parse_known_args(argv)

    from pytorch_distributed_nn_tpu.config import get_config, parse_overrides
    from pytorch_distributed_nn_tpu.inference import generate
    from pytorch_distributed_nn_tpu.models import get_model

    cfg = get_config(args.preset, **parse_overrides(rest))
    model = get_model(cfg.model)

    tokenizer = None
    eos_token = None
    if args.tokenizer:
        import transformers

        if args.tokenizer.endswith(".json"):
            tokenizer = transformers.PreTrainedTokenizerFast(
                tokenizer_file=args.tokenizer
            )
        else:
            tokenizer = transformers.AutoTokenizer.from_pretrained(
                args.tokenizer
            )
        eos_token = tokenizer.eos_token_id
        if eos_token is None:
            # a bare tokenizer.json carries no special-token config;
            # recover the conventional eos from the vocab so "eos stops
            # generation" holds, and say so if it can't
            vocab = tokenizer.get_vocab()
            for cand in ("</s>", "<|endoftext|>", "<eos>", "[SEP]"):
                if cand in vocab:
                    tokenizer.eos_token = cand
                    eos_token = vocab[cand]
                    break
            else:
                print("[generate] tokenizer defines no eos token; "
                      "generation will not early-stop", file=sys.stderr)
        ids = tokenizer.encode(args.prompt)
        if not ids:
            print("tokenizer produced an empty prompt", file=sys.stderr)
            return 1
        prompt = jnp.asarray([ids], jnp.int32)
    else:
        prompt = jnp.asarray(
            [[int(t) for t in args.prompt.split()]], jnp.int32
        )

    if args.checkpoint_dir:
        cfg.checkpoint_dir = args.checkpoint_dir
        cfg.steps = 0  # Trainer restores; no training
        from pytorch_distributed_nn_tpu.train.trainer import Trainer

        trainer = Trainer(cfg)
        if trainer.ckpt is None or trainer.ckpt.latest_step() is None:
            print(f"no checkpoint found in {args.checkpoint_dir}",
                  file=sys.stderr)
            return 1
        params = jax.device_get(trainer.state.params)
        trainer.close()
    else:
        print("[generate] no --checkpoint-dir: random init (smoke test)",
              file=sys.stderr)
        params = model.init(
            jax.random.key(cfg.seed), prompt, train=False
        )["params"]

    mesh = None
    if args.tp > 1:
        from pytorch_distributed_nn_tpu.runtime.mesh import (
            MeshSpec,
            make_mesh,
        )

        mesh = make_mesh(MeshSpec(tensor=args.tp, data=-1))

    rng = (jax.random.key(args.seed)
           if args.temperature > 0 else None)
    out = generate(model, params, prompt, args.max_new,
                   temperature=args.temperature, top_k=args.top_k,
                   top_p=args.top_p, rng=rng, eos_token=eos_token,
                   mesh=mesh, prefill_chunk=args.prefill_chunk)
    ids = [int(t) for t in np.asarray(out)[0]]
    if tokenizer is not None:
        print(tokenizer.decode(ids, skip_special_tokens=True))
    else:
        print(" ".join(str(t) for t in ids))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
