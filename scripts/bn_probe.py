"""BN microbenchmark: what does flax BatchNorm fwd+bwd actually cost on
the chip, and how many HBM passes does XLA's lowering make?

Round-5 groundwork for the fused BN-statistics Pallas kernel (VERDICT r4
Next #1): before writing a kernel, establish (a) achieved GB/s of the
XLA lowering per representative ResNet-50 shape, (b) the pass count from
the optimized HLO, so the kernel targets the real gap, not a guessed one.

Usage:  python scripts/bn_probe.py [--hlo] [--steps 20]
"""

from __future__ import annotations

import argparse
import re
import time

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

# the distinct (H, W, C) BN input planes in ResNet-50 at 224px, with
# multiplicity (how many BN layers see that shape), b=128
SHAPES = [
    # (H, W, C, count)
    (112, 112, 64, 1),   # stem
    (56, 56, 64, 6),     # stage0 conv1/conv2 x3
    (56, 56, 256, 4),    # stage0 conv3 x3 + proj
    (28, 28, 128, 8),    # stage1 conv1/conv2 x4
    (28, 28, 512, 5),    # stage1 conv3 x4 + proj
    (14, 14, 256, 12),   # stage2 conv1/conv2 x6
    (14, 14, 1024, 7),   # stage2 conv3 x6 + proj
    (7, 7, 512, 6),      # stage3 conv1/conv2 x3
    (7, 7, 2048, 4),     # stage3 conv3 x3 + proj
]


def bn_fwd_bwd(batch: int, h: int, w: int, c: int, dtype=jnp.bfloat16):
    """Train-mode BN fwd + bwd with a REAL cotangent array (dy is an
    input, not a constant-foldable ones), mirroring its position inside
    a network's backward pass."""
    bn = nn.BatchNorm(use_running_average=False, momentum=0.9,
                      epsilon=1e-5, dtype=dtype, param_dtype=jnp.float32)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, h, w, c), dtype)
    dy = jnp.asarray(rng.randn(batch, h, w, c), dtype)
    variables = bn.init(jax.random.key(0), x)
    params = variables["params"]

    def apply_fn(params, x):
        y, upd = bn.apply({"params": params}, x, mutable=["batch_stats"])
        return y, upd["batch_stats"]

    @jax.jit
    def step(params, x, dy):
        (y, stats), vjp = jax.vjp(lambda p, x: apply_fn(p, x), params, x)
        dparams, dx = vjp((dy, jax.tree.map(jnp.zeros_like, stats)))
        # scalar probes so nothing is dead-code-eliminated, everything
        # fenced by one device_get
        probe = (y.astype(jnp.float32).ravel()[0]
                 + dx.astype(jnp.float32).ravel()[0]
                 + dparams["scale"][0] + stats["mean"][0])
        return probe, y, dx, dparams, stats

    return step, params, x, dy


def time_step(step, params, x, dy, steps=20):
    out = step(params, x, dy)
    float(jax.device_get(out[0]))  # compile + fence
    t0 = time.perf_counter()
    for _ in range(steps):
        out = step(params, x, dy)
    float(jax.device_get(out[0]))
    return (time.perf_counter() - t0) / steps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hlo", action="store_true")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--shapes", type=str, default="")
    args = ap.parse_args()

    print(f"backend={jax.default_backend()} devices={jax.devices()}")
    total_ms = 0.0
    rows = []
    shapes = SHAPES
    if args.shapes:
        shapes = []
        for spec in args.shapes.split(";"):
            h, w, c, cnt = (int(v) for v in spec.split(","))
            shapes.append((h, w, c, cnt))
    for h, w, c, count in shapes:
        step, params, x, dy = bn_fwd_bwd(args.batch, h, w, c)
        dt = time_step(step, params, x, dy, args.steps)
        nbytes = np.prod(x.shape) * 2  # bf16
        # minimal-traffic model: fwd reads x (stats) + reads x, writes y
        # (normalize); bwd reads x+dy (sums) + reads x+dy, writes dx
        # (apply) = 5 reads + 2 writes of one activation plane.
        layer_ms = dt * 1e3
        total_ms += layer_ms * count
        gbs = nbytes * 7 / dt / 1e9
        rows.append((h, w, c, count, layer_ms, gbs))
        print(f"({args.batch},{h:4d},{w:4d},{c:4d}) x{count:2d}: "
              f"{layer_ms:7.3f} ms  ({gbs:6.1f} GB/s at 7-pass model)")
        if args.hlo:
            txt = step.lower(params, x, dy).compile().as_text()
            fusions = [ln.strip() for ln in txt.splitlines()
                       if ("fusion(" in ln or "fusion." in ln)
                       and "ENTRY" not in ln]
            print(f"  --- optimized HLO fusion roots ({len(fusions)}):")
            for ln in fusions:
                print("   ", ln[:160])
    print(f"\nweighted total (all 53 BN layers): {total_ms:.1f} ms/step")


if __name__ == "__main__":
    main()
