#!/usr/bin/env python
"""AOT-validate the TRUE Llama-3-8B config-5 layout on a virtual
v5e-16 topology (VERDICT.md round-1 Missing #5 / Next #6).

No pod is available here, so nothing is executed: the full 8B train
step (ZeRO-3 + remat, the real ``llama3_8b_zero`` preset) is lowered
and compiled for a 16-device mesh of virtual CPU devices with every
input abstract — zero bytes of parameters materialize. The compile
proves the SPMD partitioner accepts the layout (sharding propagation,
collective insertion) and its buffer assignment pins the per-chip
STATE bytes exactly (params + optimizer moments, dtype- and
sharding-exact).

The fits-in-HBM verdict uses those exact state bytes plus an ANALYTIC
activation model for the TPU execution path (remat boundaries + flash
attention + chunked xent). The CPU compile's temp bytes are reported
too but only as a non-representative upper bound: the CPU lowering
runs DENSE attention (no Pallas flash on host) and schedules for
speed, not memory — round 2's first full-8B compile measured 208 GiB
of CPU temps against a ~6 GiB analytic TPU activation peak, almost
all of it (B, H, T, T) dense-attention scores that the TPU path never
materializes.

Usage:
    python scripts/validate_8b_layout.py [--devices 16] [--hbm-gb 16]
        [--analytic-only] [--out LAYOUT_8B.json]
        [--a.b config overrides ...]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")  # run from repo root without install


def analytic_activation_bytes(cfg, *, n_batch_shards: int,
                              layer_params: int) -> dict:
    """Per-chip activation/transient bytes of the TPU execution path.

    Model: remat keeps only per-layer boundary activations live through
    the backward; inside the one layer being recomputed, flash
    attention is O(B*T*d) (never (T, T) scores) and the SwiGLU MLP
    holds two (B, T, ff) intermediates; the loss keeps one
    (B, chunk, V) f32 logits block + its cotangent; ZeRO-3 keeps the
    current + prefetched layer's gathered params in compute dtype; the
    gradient tree adds one sharded f32 copy of the params plus one
    layer's unsharded f32 transient before its reduce-scatter.
    """
    e = cfg.model.extra
    L = e.get("num_layers", 32)
    d = e.get("d_model", 4096)
    ff = e.get("mlp_dim", 14336)
    V = e.get("vocab_size", cfg.data.vocab_size)
    B, T = cfg.data.batch_size, cfg.data.seq_len
    accum = max(cfg.parallel.grad_accum, 1)
    comp = 2  # bf16 compute dtype bytes
    B_loc = max(B // (n_batch_shards * accum), 1)
    chunk = min(cfg.xent_chunk or T, T)
    return {
        "boundary_acts": L * B_loc * T * d * comp,
        "layer_recompute_peak": B_loc * T * max(4 * d, 2 * ff) * comp,
        "logits_block": 2 * B_loc * chunk * V * 4,  # fwd + cotangent
        "gathered_layer_params": 2 * layer_params * comp,
        "layer_grad_transient": layer_params * 4,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=16)
    ap.add_argument("--hbm-gb", type=float, default=16.0,
                    help="per-chip HBM budget (v5e: 16)")
    ap.add_argument("--analytic-only", action="store_true",
                    help="skip the compile (exact-state bytes then come "
                         "from the sharding math alone)")
    ap.add_argument("--out", default="",
                    help="also write the result JSON here")
    args, rest = ap.parse_known_args(argv)

    import jax

    # virtual topology BEFORE any backend use (sitecustomize would
    # otherwise pick the axon TPU — or hang when its tunnel is down)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", args.devices)

    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_nn_tpu.config import get_config, parse_overrides
    from pytorch_distributed_nn_tpu.models import get_model
    from pytorch_distributed_nn_tpu.parallel.zero import (
        lower_zero_train_step,
    )
    from pytorch_distributed_nn_tpu.runtime.mesh import make_mesh
    from pytorch_distributed_nn_tpu.train.losses import get_loss_fn
    from pytorch_distributed_nn_tpu.train.optim import make_optimizer
    from pytorch_distributed_nn_tpu.train.state import TrainState

    cfg = get_config("llama3_8b_zero", **parse_overrides(rest))
    mesh = make_mesh(cfg.mesh.resolve(args.devices))
    model = get_model(cfg.model)
    tx = make_optimizer(cfg.optim, total_steps=cfg.steps)
    loss_fn = get_loss_fn(cfg.data.dataset)

    B, T = cfg.data.batch_size, cfg.data.seq_len
    x_spec = jax.ShapeDtypeStruct((B, T), jnp.int32)
    y_spec = jax.ShapeDtypeStruct((B, T), jnp.int32)

    def abstract_state():
        variables = model.init(jax.random.key(0),
                               jnp.zeros((1, T), jnp.int32), train=False)
        return TrainState.create(
            apply_fn=model.apply, params=variables["params"], tx=tx,
            rng=jax.random.key(1),
        )

    t0 = time.time()
    state = jax.eval_shape(abstract_state)
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree.leaves(state.params))
    print(f"# abstract state built: {n_params / 1e9:.2f}B params "
          f"({time.time() - t0:.0f}s)", file=sys.stderr)

    # ---- exact per-chip STATE bytes from the actual shardings --------
    from pytorch_distributed_nn_tpu.parallel.zero import state_shardings
    from pytorch_distributed_nn_tpu.runtime.mesh import data_axis_size

    shardings = state_shardings(state, mesh,
                                stage=cfg.parallel.zero_stage)

    def shard_bytes(leaf, sh):
        local = sh.shard_shape(tuple(leaf.shape))
        return int(np.prod(local or (1,))) * leaf.dtype.itemsize

    state_b = sum(
        shard_bytes(leaf, sh) for leaf, sh in zip(
            jax.tree.leaves(state), jax.tree.leaves(shardings)
        )
    )

    # one decoder layer's param count (for gather/grad transients)
    layer_params = sum(
        int(np.prod(leaf.shape))
        for path, leaf in
        jax.tree_util.tree_flatten_with_path(state.params)[0]
        if any(getattr(k, "key", "") == "layer0" for k in path)
    )

    acts = analytic_activation_bytes(
        cfg, n_batch_shards=data_axis_size(mesh),
        layer_params=layer_params,
    )
    grads_shard_b = sum(
        int(np.prod(sh.shard_shape(tuple(leaf.shape)) or (1,))) * 4
        for leaf, sh in zip(jax.tree.leaves(state.params),
                            jax.tree.leaves(shardings.params))
    )
    analytic_b = state_b + grads_shard_b + sum(acts.values())
    budget = args.hbm_gb * (1 << 30)

    def gib(b):
        return round(b / (1 << 30), 3)

    rec = {
        "metric": "llama3-8b zero-3 per-chip memory (AOT, virtual "
                  f"{args.devices}-chip mesh)",
        "value": gib(analytic_b),
        "unit": "GiB/chip",
        "vs_baseline": round(analytic_b / budget, 3),
        "n_params_b": round(n_params / 1e9, 3),
        "state_exact_gib": gib(state_b),
        "grads_shard_gib": gib(grads_shard_b),
        "activations_gib": {k: gib(v) for k, v in acts.items()},
        "hbm_budget_gib": args.hbm_gb,
        "fits": bool(analytic_b <= budget),
        "mesh": dict(mesh.shape),
        "batch_global": B, "seq_len": T,
        "xent_chunk": cfg.xent_chunk, "remat": cfg.model.remat,
        "grad_accum": max(cfg.parallel.grad_accum, 1),
    }

    # ---- AOT compile: SPMD-layout proof + state-bytes cross-check ----
    if not args.analytic_only:
        lowered = lower_zero_train_step(
            mesh, loss_fn, state, x_spec, y_spec,
            stage=cfg.parallel.zero_stage,
            accum=max(cfg.parallel.grad_accum, 1),
        )
        print(f"# lowered ({time.time() - t0:.0f}s); compiling (SPMD "
              f"partitioning + buffer assignment)...", file=sys.stderr)
        mem = lowered.compile().memory_analysis()
        print(f"# compiled OK ({time.time() - t0:.0f}s)", file=sys.stderr)
        arg_b = int(mem.argument_size_in_bytes)
        batch_b = 2 * B * T * 4 // max(data_axis_size(mesh), 1)
        rec["compiled"] = {
            "spmd_partitioning": "ok",
            "argument_gib": gib(arg_b),
            "output_gib": gib(int(mem.output_size_in_bytes)),
            "cpu_temp_gib_upper_bound": gib(int(mem.temp_size_in_bytes)),
            "note": "CPU lowering: dense attention + speed-first "
                    "scheduling; temp bytes are NOT the TPU activation "
                    "footprint (see module docstring)",
        }
        # arguments = state + the two token batches; cross-check the
        # sharding math against the compiler's buffer assignment
        drift = abs(arg_b - (state_b + batch_b)) / max(arg_b, 1)
        rec["compiled"]["state_bytes_drift"] = round(drift, 4)
        if drift > 0.02:
            print(f"# WARNING: sharding-math state bytes differ from "
                  f"compiler argument bytes by {drift:.1%}",
                  file=sys.stderr)

    print(json.dumps(rec))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
    if not rec["fits"]:
        print(f"# LAYOUT DOES NOT FIT: {gib(analytic_b)} GiB/chip > "
              f"{args.hbm_gb} GiB", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
