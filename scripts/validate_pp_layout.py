#!/usr/bin/env python
"""AOT-validate the config-4 pipeline layout on a virtual v5e-16
(VERDICT r3 Missing #4 / Next #6 — the LAYOUT_8B.json treatment for
``transformer_lm_pp``).

No pod is available, so nothing is timed: the TRUE preset (GPT-2-small
Transformer-LM, global batch 64, seq 1024, pipe=4 x data=4 on 16
virtual CPU devices) is placed and its train step compiled through the
SPMD partitioner for all THREE schedules — gpipe, 1f1b, and interleaved
v=3 (12 layers / 4 stages) — proving sharding propagation + collective insertion accept each
layout at pod shape. Per schedule the record carries:

- the compiler's buffer assignment (argument/temp bytes, whole-mesh
  CPU compile — an upper bound, see LAYOUT_8B caveats);
- the ANALYTIC per-chip activation model keyed by each schedule's
  OWN depth table: gpipe holds all M microbatch boundaries, 1f1b
  holds ``Schedule.max_in_flight`` = min(M, 2S-1), interleaved holds
  ``InterleavedSchedule.act_depth`` chunk-boundaries (the v x cost
  VERDICT flagged: act_depth grows ~v-fold in chunk units);
- the tick-table bubble fraction vs the closed-form model
  ((S-1)/(M+S-1) for gpipe/1f1b; ~1/v of that for interleaved) — the
  schedule tables must reproduce the theory EXACTLY, same cost model
  as tests/test_pipeline_schedule.py.

Usage:
    python scripts/validate_pp_layout.py [--devices 16] [--hbm-gb 16]
        [--out LAYOUT_PP.json] [--a.b config overrides ...]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")  # run from repo root without install


def bubble_fraction_from_tables(schedule, *, v: int = 1) -> float:
    """Idle fraction under the tick cost model (a tick costs the max
    live-unit count over devices; one chunk unit = 1/v plain stage —
    same model as tests/test_pipeline_schedule.py's bubble proof)."""
    import numpy as np

    from pytorch_distributed_nn_tpu.parallel.pipeline_schedule import (
        NO_OP,
    )

    if v == 1:
        live = ((schedule.fwd != NO_OP).astype(int)
                + (schedule.bwd != NO_OP).astype(int))
    else:
        live = ((schedule.fwd_chunk != NO_OP).astype(int)
                + (schedule.bwd_chunk != NO_OP).astype(int))
    cost_plain = float(np.sum(live.max(axis=1))) / v
    work = 2.0 * schedule.n_micro
    return (cost_plain - work) / cost_plain


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int, default=16)
    ap.add_argument("--hbm-gb", type=float, default=16.0)
    ap.add_argument("--out", default="")
    args, rest = ap.parse_known_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", args.devices)

    import numpy as np

    from pytorch_distributed_nn_tpu.config import get_config, parse_overrides
    from pytorch_distributed_nn_tpu.models import get_model
    from pytorch_distributed_nn_tpu.parallel.pipeline import (
        make_pipeline_train_step,
    )
    from pytorch_distributed_nn_tpu.parallel.pipeline_schedule import (
        interleaved_1f1b,
        one_f_one_b,
    )
    from pytorch_distributed_nn_tpu.runtime.mesh import make_mesh
    from pytorch_distributed_nn_tpu.train.losses import get_loss_fn
    from pytorch_distributed_nn_tpu.train.optim import make_optimizer
    from pytorch_distributed_nn_tpu.train.state import TrainState

    base = get_config("transformer_lm_pp", **parse_overrides(rest))
    mesh = make_mesh(base.mesh.resolve(args.devices))
    S = mesh.shape["pipe"]
    M = base.parallel.microbatches
    B, T = base.data.batch_size, base.data.seq_len
    budget = args.hbm_gb * (1 << 30)

    model = get_model(base.model)
    loss_fn = get_loss_fn(base.data.dataset)
    rng = jax.random.key(0)
    import jax.numpy as jnp

    variables = model.init(rng, jnp.zeros((1, T), jnp.int32),
                           train=False)
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree.leaves(variables["params"]))
    print(f"# model: {n_params/1e6:.1f}M params, mesh {dict(mesh.shape)}, "
          f"B={B} T={T} M={M}", file=sys.stderr)

    # per-layer boundary activation (one microbatch, bf16 compute)
    d = getattr(model, "d_model", 768)
    comp = 2
    mb_boundary = (B // M) * T * d * comp

    records = {}
    # interleaved v: layers must divide S*v — the TRUE 12-layer model on
    # 4 stages takes v=3 (12 = 4 x 3), not the generic v=2
    for sched_name, v in (("gpipe", 1), ("1f1b", 1), ("interleaved", 3)):
        cfg = get_config("transformer_lm_pp", **parse_overrides(rest))
        cfg.parallel.pipeline_schedule = sched_name
        cfg.parallel.pipe_chunks = v if sched_name == "interleaved" else 1
        t0 = time.time()
        tx = make_optimizer(cfg.optim, total_steps=cfg.steps)
        state = TrainState.create(
            apply_fn=model.apply, params=variables["params"], tx=tx,
            model_state={k: v2 for k, v2 in variables.items()
                         if k != "params"},
            rng=jax.random.key(1),
        )
        step_fn, place_fn = make_pipeline_train_step(cfg, mesh, loss_fn,
                                                     model)
        placed = place_fn(state)
        # EXACT per-chip state bytes from the placed shardings (params
        # + both Adam moments, stage-stacked layout included) — the
        # worst chip, since edge stages carry the embed/head tables
        per_dev = {d: 0 for d in mesh.devices.flat}
        for leaf in jax.tree.leaves(placed):
            if not hasattr(leaf, "sharding"):
                continue
            shard_elems = int(np.prod(
                leaf.sharding.shard_shape(tuple(leaf.shape)) or (1,)))
            nbytes = shard_elems * leaf.dtype.itemsize
            for d in leaf.sharding.device_set:
                per_dev[d] += nbytes
        state_chip_b = max(per_dev.values())
        x = jax.ShapeDtypeStruct((B, T), jnp.int32)
        lowered = step_fn.jitted().lower(placed, x, x)
        ma = lowered.compile().memory_analysis()
        mem = {
            "argument_gib": round(
                ma.argument_size_in_bytes / (1 << 30), 3),
            "temp_gib_whole_mesh_cpu_upper_bound": round(
                ma.temp_size_in_bytes / (1 << 30), 3),
        }
        # analytic per-chip activation depth, schedule-exact
        if sched_name == "gpipe":
            depth_unit, depth = "microbatch boundaries", M
            bubble_tbl = None
        elif sched_name == "1f1b":
            tbl = one_f_one_b(S, M)
            depth_unit, depth = "microbatch boundaries", tbl.max_in_flight
            bubble_tbl = bubble_fraction_from_tables(tbl)
        else:
            tbl = interleaved_1f1b(S, v, M)
            # act_depth counts CHUNK boundaries; a chunk boundary is the
            # same (B/M, T, d) tensor — the v x cost VERDICT flagged
            depth_unit, depth = "chunk boundaries", tbl.act_depth
            bubble_tbl = bubble_fraction_from_tables(tbl, v=v)
        # per-chip total = exact state (params + Adam m, v — the
        # placed-sharding bytes above) + one f32 grad copy of the
        # worst stage's params (state/3 ≈ one param-sized tree) +
        # schedule-depth activations
        grad_b = state_chip_b // 3
        acts_b = depth * mb_boundary + state_chip_b + grad_b
        # fill+drain cost (S-1)/v plain-stage units per direction over
        # 2M units of work: frac = ((S-1)/v) / (M + (S-1)/v)
        fill = (S - 1) / v
        bubble_model = fill / (M + fill)
        records[sched_name] = {
            "schedule": sched_name,
            "act_depth": depth,
            "act_depth_unit": depth_unit,
            "analytic_act_gib_per_chip": round(
                depth * mb_boundary / (1 << 30), 4),
            "state_exact_gib_worst_chip": round(
                state_chip_b / (1 << 30), 3),
            "analytic_total_gib_per_chip": round(acts_b / (1 << 30), 3),
            "fits": bool(acts_b <= budget),
            "bubble_closed_form": round(bubble_model, 4),
            **({"bubble_from_tick_tables": round(bubble_tbl, 4)}
               if bubble_tbl is not None else {}),
            **mem,
            "compile_seconds": round(time.time() - t0, 1),
        }
        print(f"# {sched_name}: {json.dumps(records[sched_name])}",
              file=sys.stderr)

    rec = {
        "metric": "transformer_lm_pp pod layout (AOT, virtual "
                  f"{args.devices}-chip mesh)",
        "n_params_m": round(n_params / 1e6, 1),
        "mesh": dict(mesh.shape),
        "batch_global": B, "seq_len": T, "microbatches": M,
        "hbm_budget_gib": args.hbm_gb,
        "schedules": records,
        "fits_all": all(r["fits"] for r in records.values()),
    }
    print(json.dumps(rec))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
    return 0 if rec["fits_all"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
