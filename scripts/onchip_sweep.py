#!/usr/bin/env python
"""One-shot on-chip round sweep (VERDICT r2 Next #1).

Two rounds of perf claims rest on round-1 self-reports because the
axon tunnel was down for all of round 2's build and judging. The
moment the tunnel answers, run THIS — it captures every on-chip
artifact in one pass, ordered so the most important land first if the
tunnel flaps again:

1. `scripts/validate_tpu_kernels.py` -> KERNELS_r{N}.json  (the Pallas
   kernel gate: flash fwd/bwd, ring block, ring backward, int8
   quantize, 128k/512k long-context — never yet recorded on real TPU)
2. `bench.py` per preset (+ decode / loader / bus_bw)       -> ONCHIP_r{N}.json

Each phase runs as a subprocess with a timeout, so a mid-sweep tunnel
drop costs that phase only; everything captured so far is still
written. Run `python scripts/onchip_sweep.py [--round N]` from the
repo root with NO platform overrides (the default backend must be the
TPU).
"""

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PRESETS = [
    "mlp_mnist", "lenet_cifar10", "resnet50_dp", "bert_base_buckets",
    "transformer_lm_pp", "llama3_8b_zero", "moe_lm_ep",
    "llama3_longcontext", "llama3_longcontext_96k",
]
# record key -> (preset, extra bench args): dispatch-bound presets get a
# second row under the device-side training loop (--multistep: k steps
# per dispatch via lax.scan) — through the tunnel the single-dispatch
# number measures round-trip latency, this one measures the chip
PRESET_VARIANTS = {
    "mlp_mnist_multistep": ("mlp_mnist",
                            ["--multistep", "50", "--steps", "20",
                             "--warmup", "100"]),
    "lenet_cifar10_multistep": ("lenet_cifar10",
                                ["--multistep", "50", "--steps", "20",
                                 "--warmup", "100"]),
}
METRICS = ["decode", "bus_bw", "loader"]


def run(cmd: list[str], timeout: float) -> dict:
    t0 = time.time()
    try:
        r = subprocess.run(cmd, cwd=REPO, capture_output=True,
                           text=True, timeout=timeout)
        return {"cmd": " ".join(cmd), "rc": r.returncode,
                "stdout": r.stdout[-20000:], "stderr": r.stderr[-4000:],
                "seconds": round(time.time() - t0, 1)}
    except subprocess.TimeoutExpired as e:
        # keep the partial output: on a mid-run tunnel flap the check
        # lines printed before the hang are the salvageable evidence
        out = e.stdout or b""
        err = e.stderr or b""
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        if isinstance(err, bytes):
            err = err.decode(errors="replace")
        return {"cmd": " ".join(cmd), "rc": None,
                "stdout": out[-20000:],
                "stderr": (err[-3000:]
                           + f"\nTIMEOUT after {timeout:.0f}s"),
                "seconds": round(time.time() - t0, 1)}


def last_json_line(stdout: str):
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=3)
    ap.add_argument("--kernel-timeout", type=float, default=1800)
    ap.add_argument("--bench-timeout", type=float, default=900)
    phase = ap.add_mutually_exclusive_group()
    phase.add_argument("--kernels-only", action="store_true",
                       help="refresh KERNELS_r{N}.json without re-running "
                            "the bench phase (kernel gate is ~10 min; the "
                            "full bench sweep is ~an hour of chip time)")
    phase.add_argument("--bench-only", action="store_true",
                       help="refresh ONCHIP_r{N}.json without re-running "
                            "the kernel gate")
    args = ap.parse_args()

    # ---- 1) kernel gate ------------------------------------------------
    if args.bench_only:
        # keep the existing artifact, but report ITS verdict — a
        # hardcoded ok=True would let a bench-only refresh after a
        # failed kernel gate exit 0 and green-out the gate (advisor r3)
        kpath = os.path.join(REPO, f"KERNELS_r{args.round:02d}.json")
        kernels = {"ok": False, "error": f"no readable {kpath}"}
        try:  # a truncated artifact must not abort the bench refresh
            with open(kpath) as f:
                kernels = json.load(f)
        except (OSError, json.JSONDecodeError):
            pass
        print(f"bench-only: kernel gate from existing artifact: "
              f"ok={kernels.get('ok')}")
    else:
        kr = run([sys.executable, "scripts/validate_tpu_kernels.py"],
                 args.kernel_timeout)
        checks = [ln for ln in kr["stdout"].splitlines()
                  if re.search(r"\b(OK|FAIL)\b", ln)]
        backend_line = next((ln for ln in kr["stdout"].splitlines()
                             if ln.startswith("backend:")), "")
        kernels = {
            "round": args.round,
            # ok requires the REAL chip: the validator exits 0 on CPU
            # fallbacks too, and a fallback pass must not certify the
            # on-chip gate this artifact exists to record
            "ok": (kr["rc"] == 0 and "ALL OK" in kr["stdout"]
                   and "tpu" in backend_line.lower()),
            "on_tpu": "tpu" in backend_line.lower(),
            "rc": kr["rc"],
            "backend_line": backend_line,
            "checks": checks,
            "seconds": kr["seconds"],
            **({"error": kr["stderr"]} if kr["rc"] != 0 else {}),
        }
        kpath = os.path.join(REPO, f"KERNELS_r{args.round:02d}.json")
        with open(kpath, "w") as f:
            json.dump(kernels, f, indent=1)
        print(f"wrote {kpath}: ok={kernels['ok']} "
              f"({len(checks)} check lines)")
        if args.kernels_only:
            return 0 if kernels.get("ok") else 1

    # ---- 2) bench sweep ------------------------------------------------
    records = {}
    for preset in PRESETS:
        cmd = [sys.executable, "bench.py", "--preset", preset]
        if preset == "llama3_longcontext_96k":
            # ~13 s/step at 96k tokens: 30 timed steps would brush the
            # bench timeout; 10 is plenty of signal at this length
            cmd += ["--steps", "10", "--warmup", "2"]
        r = run(cmd, args.bench_timeout)
        records[preset] = last_json_line(r["stdout"]) or {
            "error": r["stderr"][-500:], "rc": r["rc"]}
        print(f"{preset}: {json.dumps(records[preset])[:160]}")
    for key, (preset, extra) in PRESET_VARIANTS.items():
        r = run([sys.executable, "bench.py", "--preset", preset] + extra,
                args.bench_timeout)
        records[key] = last_json_line(r["stdout"]) or {
            "error": r["stderr"][-500:], "rc": r["rc"]}
        print(f"{key}: {json.dumps(records[key])[:160]}")
    metric_runs = [(m, m, []) for m in METRICS]
    # decode again at serving-throughput batch: decode is HBM-bandwidth
    # bound, so tokens/s scales near-linearly in batch until compute
    # takes over (r3 sweep: 5.7k/18.6k/48k/96.6k/175-181k/345k/500k
    # tok/s at b=8/32/64/128/256/512/1024 — the b=256 spread is
    # run-to-run tunnel variance; ONCHIP's record is authoritative —
    # OOM at 2048); b=8 stays the latency-series record, b=256 is the
    # throughput story
    metric_runs.append(("decode_b256", "decode",
                        ["--per-chip-batch", "256"]))
    # the flagship: the TRUE 8.03B Llama-3, weight-only int8 (fits the
    # single chip's HBM). The FULL batch series is recorded so every
    # number BASELINE/README headline has a JSON record behind it
    # (VERDICT r4 Weak #2): b=1 interactive latency, b=8/32/64 the
    # latency-throughput curve, b=128 the bf16-cache capacity edge;
    # then the int8 KV cache (nn/attention.py cache_dtype="int8")
    # extends the curve to its own b=256 edge (b=288 OOMs).
    for b in (1, 8, 32, 64, 128):
        metric_runs.append((f"decode_8b_int8_b{b}", "decode",
                            ["--real-8b-int8", "--per-chip-batch",
                             str(b)]))
    for b in (128, 256):
        # b=256 needs chunked prefill: the fused-projection one-shot
        # (B, P) prefill peak exceeds HBM at the capacity edge
        chunk = ["--prefill-chunk", "32"] if b >= 256 else []
        metric_runs.append((f"decode_8b_int8_kv8_b{b}", "decode",
                            ["--real-8b-int8", "--kv-int8",
                             "--per-chip-batch", str(b)] + chunk))
    # whole-model int8 quality (VERDICT r4 Missing #3): the trained
    # scaled int8-vs-bf16 NLL delta, and the TRUE-8B eval-path record
    # (synthetic weights — labeled in the record)
    metric_runs.append(("quality_int8_delta", "quality",
                        ["--steps", "16"]))
    metric_runs.append(("quality_8b_evalpath", "quality",
                        ["--real-8b-int8", "--steps", "16"]))
    for key, metric, extra in metric_runs:
        cmd = [sys.executable, "bench.py", "--metric", metric] + extra
        if metric == "loader":
            cmd += ["--preset", "resnet50_dp"]
        elif metric == "bus_bw":
            # THE BASELINE bus-bw claim is BERT fused buckets
            cmd += ["--preset", "bert_base_buckets"]
        r = run(cmd, args.bench_timeout)
        records[f"metric:{key}"] = last_json_line(r["stdout"]) or {
            "error": r["stderr"][-500:], "rc": r["rc"]}
        print(f"{key}: {json.dumps(records[f'metric:{key}'])[:160]}")

    opath = os.path.join(REPO, f"ONCHIP_r{args.round:02d}.json")
    out = {"round": args.round, "records": records}
    try:
        # provenance notes (re-measurement history) are hand-curated in
        # the artifact; a routine re-sweep must not silently destroy
        # them — carry them forward with a (non-accumulating) stamp
        with open(opath) as f:
            prior = json.load(f).get("provenance")
        stamp = " [records since replaced by a full re-sweep]"
        if prior:
            out["provenance"] = (prior if prior.endswith(stamp)
                                 else prior + stamp)
    except (OSError, json.JSONDecodeError):
        pass
    with open(opath, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {opath}")
    return 0 if kernels.get("ok") else 1


if __name__ == "__main__":
    raise SystemExit(main())
