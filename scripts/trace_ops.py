"""Per-op device-time breakdown of a preset's train step, from a
perfetto trace (the r4/r5 ResNet MFU analyses are built on this).

Usage: python scripts/trace_ops.py --preset resnet50_dp \
           --set 'model.extra={"stem":"s2d"}' [--steps 10] [--top 30]

Prints the device-side op-name buckets (fusion kinds) sorted by total
time, normalized per step, plus the all-op total (= device ms/step).
"""

from __future__ import annotations

import argparse
import gzip
import json
import glob
import os
import re
import shutil
import sys
import tempfile
from collections import defaultdict

sys.path.insert(0, ".")

from pytorch_distributed_nn_tpu.runtime.platform import (  # noqa: E402
    apply_platform_overrides,
)

apply_platform_overrides()

import jax  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="resnet50_dp")
    ap.add_argument("--set", action="append", default=[],
                    dest="overrides")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--per-chip-batch", type=int, default=0)
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--keep", default="",
                    help="keep the trace dir at this path")
    ap.add_argument("--full", action="store_true",
                    help="also print the top individual op names")
    args = ap.parse_args()

    from pytorch_distributed_nn_tpu.config import get_config, \
        parse_overrides
    from pytorch_distributed_nn_tpu.train.trainer import Trainer
    from pytorch_distributed_nn_tpu.utils.profiling import xprof_trace

    import bench

    overrides = parse_overrides(["--" + kv for kv in args.overrides])
    cfg = get_config(args.preset, **overrides)
    per_chip = (args.per_chip_batch
                or bench.PER_CHIP_BATCH.get(args.preset, 8))
    n_chips = len(jax.devices())
    cfg.data.batch_size = per_chip * n_chips
    cfg.steps = args.warmup + args.steps + 1
    cfg.log_every = 0
    trainer = Trainer(cfg)
    batch = trainer.loader.batch_at(0)
    state = trainer.state
    for _ in range(args.warmup):
        state, m = trainer.step_fn(state, *batch)
    float(jax.device_get(m["loss"]))

    trace_dir = args.keep or tempfile.mkdtemp(prefix="trace_ops_")
    with xprof_trace(trace_dir, perfetto=True):
        for _ in range(args.steps):
            state, m = trainer.step_fn(state, *batch)
        float(jax.device_get(m["loss"]))

    paths = sorted(glob.glob(
        os.path.join(trace_dir, "**", "perfetto_trace.json.gz"),
        recursive=True))
    if not paths:
        raise SystemExit(f"no perfetto trace under {trace_dir}")
    with gzip.open(paths[-1]) as f:
        tr = json.load(f)
    events = tr["traceEvents"] if isinstance(tr, dict) else tr

    # device-side op slices live on "XLA Ops" / TensorCore tracks; skip
    # python/host slices ($...), step markers, and async 'end:' pairs
    pid_names = {}
    tid_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e["pid"]] = e["args"].get("name", "")
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tid_names[(e["pid"], e["tid"])] = e["args"].get("name", "")

    device_tids = {k for k, v in tid_names.items()
                   if "XLA Ops" in v or "TensorCore" in v}
    buckets = defaultdict(float)
    total_us = 0.0
    n = 0
    for e in events:
        if e.get("ph") != "X":
            continue
        if device_tids and (e.get("pid"), e.get("tid")) not in device_tids:
            continue
        name = e.get("name", "")
        if name.startswith("$") or name.startswith("end: "):
            continue
        kind = re.sub(r"[.\d]+(\.clone)?$", "", name)
        dur = float(e.get("dur", 0.0))
        buckets[kind] += dur
        total_us += dur
        n += 1
    if not device_tids:
        print("NOTE: no 'XLA Ops' thread found; aggregated all X slices")
    per_step = total_us / args.steps / 1e3
    print(f"\ndevice ops: {n} slices, {per_step:.2f} ms/step total")
    print(f"{'bucket':44s} {'ms/step':>9s} {'%':>6s}")
    for kind, us in sorted(buckets.items(), key=lambda kv: -kv[1])[
            :args.top]:
        print(f"{kind:44s} {us/args.steps/1e3:9.3f} "
              f"{us/total_us*100:6.1f}")
    if args.full:
        full = defaultdict(float)
        for e in events:
            if e.get("ph") != "X":
                continue
            if device_tids and (e.get("pid"),
                                e.get("tid")) not in device_tids:
                continue
            name = e.get("name", "")
            if name.startswith("$") or name.startswith("end: "):
                continue
            full[name] += float(e.get("dur", 0.0))
        print(f"\ntop {args.top} individual ops:")
        for name, us in sorted(full.items(), key=lambda kv: -kv[1])[
                :args.top]:
            print(f"{name:58s} {us/args.steps/1e3:9.3f}")
    if not args.keep:
        shutil.rmtree(trace_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
