#!/usr/bin/env python
"""Render Causeway request traces: waterfalls, critical paths, rollups.

Input is any of the places spans land (obs/trace.py emits them):

- a JSON file holding a span list, or a (merged) Chrome trace whose
  ``cat == "trace"`` events carry spans in ``args`` (the
  ``obs.trace.spans_to_chrome`` / ``obs.span.merge_chrome_traces``
  round trip);
- a metrics JSONL file — every ``event == "trace_span"`` record;
- a live store: ``--store host:port --ranks N`` pulls every published
  per-host buffer (``obs.aggregate.collect_spans``) — the
  process-fleet path, where each ``fleet_worker`` publishes its own
  spans at ``trace/<idx>``.

Per trace: the waterfall (one bar per duration span, offset from the
trace's first instant) and the critical path — every instant of the
observed extent attributed to exactly one segment (transfer > failover
> restore > prefill > decode > queued; uncovered time is ``stitch``),
so the per-segment seconds provably sum to end-to-end latency.
``--rollup`` prints the fleet view per SLO latency band instead.

Usage:
    python scripts/obs_trace.py spans.json               # all traces
    python scripts/obs_trace.py merged.trace.json --trace a3f0
    python scripts/obs_trace.py run.jsonl --rollup
    python scripts/obs_trace.py --store 127.0.0.1:29500 --ranks 4
    python scripts/obs_trace.py --selftest               # tier-1 gate

``--selftest`` is the deterministic no-accelerator acceptance drill
(tier-1 via tests/test_quality.py): one request through a
disaggregated fleet with a ``kill_transfer@`` chaos kill mid-stream
must yield ONE merged trace whose queued/prefill/transfer/failover/
decode segments sum to the measured end-to-end latency within 1%,
with the re-admitted decode leg linked to the original trace — and the
whole drill must produce byte-identical canonical trace JSON when run
twice with the same seed.
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, ".")  # run from repo root without install

from pytorch_distributed_nn_tpu.obs import critpath  # noqa: E402
from pytorch_distributed_nn_tpu.obs import trace as tracemod  # noqa: E402

BAR_W = 40


def load_spans(path: str) -> list[dict]:
    """Span dicts from a span-list JSON, a Chrome trace, or a metrics
    JSONL stream (``kind == "trace_span"`` events)."""
    with open(path) as f:
        head = f.read(1)
        f.seek(0)
        if head in ("[", "{"):
            try:
                doc = json.load(f)
            except json.JSONDecodeError:
                doc = None
            if isinstance(doc, dict):
                return critpath.spans_from_chrome(
                    doc.get("traceEvents", []))
            if isinstance(doc, list):
                if doc and doc[0].get("ph"):
                    return critpath.spans_from_chrome(doc)
                return doc
            f.seek(0)
        spans = []
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line — the JSONL contract
            if ev.get("event") == "trace_span":
                spans.append({k: v for k, v in ev.items()
                              if k not in ("event", "time", "process")})
        return spans


def pull_spans(endpoint: str, ranks: int, namespace: str) -> list[dict]:
    from pytorch_distributed_nn_tpu.obs import aggregate
    from pytorch_distributed_nn_tpu.serve.store import (
        PrefixStore,
        make_store,
    )

    client = make_store(endpoint)
    ps = PrefixStore(client, namespace) if namespace else client
    try:
        return aggregate.collect_spans(ps, range(ranks))
    finally:
        try:
            client.close()
        except OSError:
            pass


def print_waterfall(spans: list[dict], trace_id: str) -> None:
    wf = critpath.waterfall(spans, trace_id)
    cp = wf["critical_path"]
    total = cp["total_s"]
    legs = ", ".join(
        f"leg{n}@{'+'.join(leg['hosts'])}"
        for n, leg in wf["legs"].items())
    print(f"== trace {trace_id} ==  {total * 1e3:.1f}ms end-to-end, "
          f"{len(wf['rows'])} span(s), {legs} "
          f"(linked={'yes' if wf['linked'] else 'NO'})")
    for row in wf["rows"]:
        if total > 0:
            lo = int(BAR_W * row["start_s"] / total)
            hi = int(BAR_W * (row["start_s"] + row["dur_s"]) / total)
            bar = " " * lo + "#" * max(hi - lo, 1)
        else:
            bar = "#"
        extra = " ".join(f"{k}={v}" for k, v in
                         sorted(row["attrs"].items())
                         if k not in ("request_id",))
        print(f"  leg{row['leg']} {row['segment']:>9} "
              f"|{bar:<{BAR_W}}| {row['dur_s'] * 1e3:8.1f}ms  {extra}")
    parts = "  ".join(
        f"{seg}={sec * 1e3:.1f}ms"
        for seg, sec in sorted(cp["segments"].items(),
                               key=lambda kv: -kv[1]))
    print(f"  critical path: {parts}  (dominant: {cp['dominant']})")


def print_rollup(spans: list[dict]) -> None:
    roll = critpath.rollup(spans)
    if not roll:
        print("no traces")
        return
    print(f"{'band':>8} {'traces':>7} {'dominant':>10}  per-segment "
          f"p50/p99 (ms)")
    for band, row in roll.items():
        segs = "  ".join(
            f"{seg}={st['p50_s'] * 1e3:.1f}/{st['p99_s'] * 1e3:.1f}"
            for seg, st in row["segments"].items())
        print(f"{band:>8} {row['traces']:>7} {row['dominant']:>10}  "
              f"{segs}")


def _render(spans: list[dict], args) -> int:
    if not spans:
        # a quiet report, not a failure: the stream simply ran with
        # Causeway unarmed (TPUNN_TRACE unset)
        print("no trace spans found")
        return 0
    trace_ids = sorted({str(s.get("trace", "")) for s in spans})
    if args.trace:
        trace_ids = [t for t in trace_ids
                     if t.startswith(args.trace)]
        if not trace_ids:
            # an explicit trace-id filter that matches nothing IS an
            # operator error — keep that loud
            print(f"no trace matching {args.trace!r}")
            return 1
    if args.json:
        if args.rollup:
            print(json.dumps(critpath.rollup(spans), indent=2))
        else:
            print(json.dumps(
                {t: critpath.waterfall(spans, t) for t in trace_ids},
                indent=2))
        return 0
    if args.rollup:
        print_rollup(spans)
        return 0
    for t in trace_ids:
        print_waterfall(spans, t)
        print()
    return 0


# ---------------------------------------------------------------------------
# --selftest: the deterministic disagg kill_transfer drill (tier-1)
# ---------------------------------------------------------------------------


def _drill() -> tuple[list[dict], float]:
    """One traced request through a disaggregated fleet with the first
    KV transfer killed mid-stream. Returns (spans, measured e2e
    seconds). The tiny 2-layer llama is the bench.py --fleet --disagg
    --selftest shape: CPU-scale, seed-pinned, greedy."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_nn_tpu.config import ModelConfig
    from pytorch_distributed_nn_tpu.models import get_model
    from pytorch_distributed_nn_tpu.obs import flight
    from pytorch_distributed_nn_tpu.runtime import chaos
    from pytorch_distributed_nn_tpu.serve import Fleet
    from pytorch_distributed_nn_tpu.serve.disagg import DisaggFleet

    vocab = 97
    model = get_model(ModelConfig(
        name="llama3_8b", compute_dtype="float32", dtype="float32",
        extra=dict(num_layers=2, d_model=64, num_heads=4,
                   num_kv_heads=2, mlp_dim=128, vocab_size=vocab)))
    params = model.init(jax.random.key(1),
                        jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    rng = np.random.default_rng(7)
    # 34 tokens = 2 full 16-token blocks: the prefill leg's chain is
    # streamable, so the decode-leg placement warm-pulls through
    # kv_transfer — where the chaos kill fires
    prompt = rng.integers(1, vocab, size=(34,)).astype(np.int32)

    tracemod.reset()
    chaos.reset()
    flight.reset_recorder(enabled=True)
    tracemod.maybe_init("1", rank=0)
    chaos.maybe_init("kill_transfer@step=1", rank=0, seed=0)
    fleet = Fleet(model, params, prefill=2, decode=2, max_slots=2,
                  max_seq_len=64, block_size=16)
    assert isinstance(fleet, DisaggFleet), type(fleet)
    ticket = fleet.submit(prompt, 6, request_id="trace-selftest-0")
    fleet.run_until_idle()
    assert ticket.ok, (ticket.status, ticket.reject_reason)
    e2e_s = ticket.t_done - ticket.t_submit
    assert any(t["outcome"] == "failed" for t in fleet.transfers), \
        f"chaos kill never hit the transfer: {fleet.transfers}"
    spans = tracemod.export_spans()
    tracemod.reset()
    chaos.reset()
    return spans, e2e_s


def _selftest() -> int:
    import tempfile

    from pytorch_distributed_nn_tpu.obs.span import merge_chrome_traces

    spans, e2e_s = _drill()
    assert spans, "armed drill emitted no spans"

    # cross-host merge path: split the spans across two chrome files
    # (as two worker hosts would write them), merge, read back — the
    # round trip must be lossless
    ids = sorted({s["trace"] for s in spans})
    assert len(ids) == 1, f"expected ONE merged trace, got {ids}"
    trace_id = ids[0]
    half = [s for s in spans if s["leg"] == 0]
    rest = [s for s in spans if s["leg"] != 0]
    assert half and rest, "drill never produced a second leg"
    with tempfile.TemporaryDirectory(prefix="tpunn-trace-") as d:
        paths = []
        for i, part in enumerate((half, rest)):
            p = f"{d}/host{i}.trace.json"
            with open(p, "w") as f:
                json.dump({"traceEvents":
                           tracemod.spans_to_chrome(part, pid=i)}, f)
            paths.append(p)
        merged = merge_chrome_traces(paths, f"{d}/merged.trace.json")
        with open(merged) as f:
            back = critpath.spans_from_chrome(
                json.load(f)["traceEvents"])
    assert len(back) == len(spans), (len(back), len(spans))

    wf = critpath.waterfall(back, trace_id)
    cp = wf["critical_path"]
    assert wf["linked"], \
        f"re-admitted leg not linked to the original trace: {wf['legs']}"
    for seg in ("queued", "prefill", "transfer", "failover", "decode"):
        assert seg in cp["segments"], \
            f"missing {seg} in critical path: {sorted(cp['segments'])}"
    total = sum(cp["segments"].values())
    assert abs(total - cp["total_s"]) < 1e-9, \
        "critical path is not a partition"
    err = abs(cp["total_s"] - e2e_s) / max(e2e_s, 1e-9)
    assert err <= 0.01, \
        (f"segments sum {cp['total_s']:.6f}s vs measured e2e "
         f"{e2e_s:.6f}s ({err:.2%} off, budget 1%)")

    # determinism gate: the same seeded drill twice must yield
    # byte-identical canonical (structure-only) trace JSON
    spans2, _ = _drill()
    a = critpath.canonical_json(spans)
    b = critpath.canonical_json(spans2)
    assert a == b, "same seed produced different canonical trace JSON"

    print_waterfall(back, trace_id)
    print(f"e2e {e2e_s * 1e3:.1f}ms vs attributed "
          f"{cp['total_s'] * 1e3:.1f}ms ({err:.2%} off)")
    print("trace selftest ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render Causeway trace waterfalls / critical "
                    "paths / fleet rollups")
    ap.add_argument("path", nargs="?",
                    help="span-list JSON, Chrome trace, or metrics "
                         "JSONL file")
    ap.add_argument("--trace", default="",
                    help="render only traces whose id starts with this")
    ap.add_argument("--rollup", action="store_true",
                    help="fleet rollup per SLO latency band instead "
                         "of per-trace waterfalls")
    ap.add_argument("--store", default="",
                    help="pull published spans from a live store "
                         "(host:port)")
    ap.add_argument("--ranks", type=int, default=4,
                    help="ranks to pull with --store")
    ap.add_argument("--namespace", default="fleet",
                    help="store key namespace (--store)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--selftest", action="store_true",
                    help="deterministic disagg kill_transfer tracing "
                         "drill (no accelerator; tier-1 gate)")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if args.store:
        return _render(pull_spans(args.store, args.ranks,
                                  args.namespace), args)
    if not args.path:
        ap.error("need a file, --store, or --selftest")
    try:
        spans = load_spans(args.path)
    except OSError as e:
        print(f"cannot read {args.path}: {e}", file=sys.stderr)
        return 1
    return _render(spans, args)


if __name__ == "__main__":
    sys.exit(main())
