#!/usr/bin/env python
"""Operate a process-backed serving fleet (serve/procfleet.py).

The multi-host deployment loop (docs/serving.md has the full runbook):

    # host A: the store every fleet word travels through
    python scripts/fleet_deploy.py store --port 7777

    # host B: coordinator + replica subprocesses
    python scripts/fleet_deploy.py start --store hostA:7777 \
        --replicas 3 --backend tiny --autoscale 1

    # disaggregated pools: prefill + decode replicas, KV handoff
    # streamed cross-process over the store wire (serve/kv_wire.py)
    python scripts/fleet_deploy.py start --store hostA:7777 \
        --fleet-prefill 1 --fleet-decode 2

    # cross-host provisioning: each worker spawn goes through the
    # template ({cmd} = the shell-quoted worker command); the worker
    # enrolls itself back through the store (pid, host, role)
    python scripts/fleet_deploy.py start --store hostA:7777 \
        --replicas 2 --spawn-template 'ssh hostC {cmd}'

    # host B died? any host: take over WITHOUT restarting workers —
    # live replicas are adopted pid-for-pid, stranded requests are
    # re-admitted with their emitted prefix, Helm's journal continues
    python scripts/fleet_deploy.py recover --store hostA:7777

    # anywhere: what does the store say the fleet looks like?
    python scripts/fleet_deploy.py status --store hostA:7777

``start``/``recover`` run until SIGINT/SIGTERM, then drain and stop.
``status`` is read-only: one JSON object from the store's own state
(membership, coordinator beat age, journal depths) — exactly what a
recovering coordinator would see.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import time

sys.path.insert(0, ".")  # run from repo root without install

from pytorch_distributed_nn_tpu.runtime.platform import (
    apply_platform_overrides,
)

apply_platform_overrides()


def _cmd_store(args) -> int:
    from pytorch_distributed_nn_tpu.runtime import native

    server = native.StoreServer(args.port)
    print(json.dumps({"event": "store_up", "port": server.port}),
          flush=True)
    stop = []
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.5)
    finally:
        server.stop()
    return 0


def _run_fleet(fleet) -> int:
    stop = []
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *a: stop.append(1))
    fleet.start()
    try:
        while not stop and not fleet.dead:
            time.sleep(0.5)
    finally:
        summary = fleet.summary()
        if not fleet.dead:
            fleet.stop()
        print(json.dumps({"event": "fleet_exit",
                          "coordinator_dead": fleet.dead,
                          **summary}, sort_keys=True), flush=True)
    # a dead coordinator is an incident, not a clean exit — the
    # operator (or a supervisor) should run `recover` next
    return 1 if fleet.dead else 0


def _cmd_start(args) -> int:
    from pytorch_distributed_nn_tpu.serve.procfleet import (
        ProcessFleet,
        TemplateProvisioner,
    )

    if bool(args.fleet_prefill) != bool(args.fleet_decode):
        print("error: disaggregation needs BOTH --fleet-prefill and "
              "--fleet-decode >= 1", file=sys.stderr)
        return 2
    provisioner = (TemplateProvisioner(args.spawn_template)
                   if args.spawn_template else None)
    fleet = ProcessFleet(
        replicas=args.replicas, backend=args.backend,
        prefill=args.fleet_prefill, decode=args.fleet_decode,
        role=args.role, provisioner=provisioner,
        preset=args.preset, ckpt=args.ckpt,
        namespace=args.namespace, store_endpoint=args.store or None,
        autoscale_spec=args.autoscale,
        heartbeat_timeout_s=args.heartbeat_timeout)
    print(json.dumps({"event": "coordinator_up", "mode": "fresh",
                      "incarnation": fleet.incarnation,
                      "disagg": fleet.disagg,
                      "store": fleet.store_endpoint,
                      "namespace": args.namespace},
                     sort_keys=True), flush=True)
    return _run_fleet(fleet)


def _cmd_recover(args) -> int:
    from pytorch_distributed_nn_tpu.serve.procfleet import ProcessFleet

    if not args.store:
        print("error: recover needs --store (the fleet's state lives "
              "there, not here)", file=sys.stderr)
        return 2
    fleet = ProcessFleet.recover_from(
        store_endpoint=args.store, namespace=args.namespace,
        backend=args.backend, preset=args.preset, ckpt=args.ckpt,
        autoscale_spec=args.autoscale,
        heartbeat_timeout_s=args.heartbeat_timeout)
    print(json.dumps({"event": "coordinator_up", "mode": "recover",
                      "incarnation": fleet.incarnation,
                      "gap_s": round(fleet.gap_s, 3),
                      "recovery": fleet.recovery,
                      "store": fleet.store_endpoint,
                      "namespace": args.namespace},
                     sort_keys=True), flush=True)
    return _run_fleet(fleet)


def _cmd_status(args) -> int:
    from pytorch_distributed_nn_tpu.serve.store import (
        PrefixStore, StoreJournal, make_store,
    )

    if not args.store:
        print("error: status needs --store", file=sys.stderr)
        return 2
    client = make_store(args.store)
    ns = PrefixStore(client, args.namespace)
    out: dict = {"store": args.store, "namespace": args.namespace}
    members = []
    if ns.check("members"):
        members = json.loads(ns.get("members", timeout_ms=2000).decode())
    out["members"] = members
    out["coordinator_incarnations"] = ns.add("coord/inc", 0)
    if ns.check("coord/beat"):
        out["coordinator_beat_age_s"] = round(
            time.time() - float(ns.get("coord/beat", timeout_ms=2000)),
            3)
    out["journal_len"] = len(StoreJournal(ns, "journal"))
    out["helm_journal_len"] = len(StoreJournal(ns, "helm"))
    beats = {}
    for m in members:
        key = f"hb/0/{m['index']}"
        if ns.check(key):
            beats[str(m["index"])] = round(
                time.time() - float(ns.get(key, timeout_ms=2000)), 3)
    out["beat_age_s"] = beats
    # Lighthouse (obs/audit.py): per-replica integrity state. Both
    # keys are absent on an unarmed fleet — status output is
    # byte-stable either way the fleet was launched.
    audits = {}
    for m in members:
        key = f"audit/{m['index']}"
        if not ns.check(key):
            continue
        p = json.loads(ns.get(key, timeout_ms=2000).decode())
        ent = dict(fingerprints=p.get("fingerprints", 0),
                   divergences=p.get("divergences", 0),
                   probe_failures=p.get("probe_failures", 0))
        if p.get("last_fp_t"):
            ent["last_fp_age_s"] = round(
                time.time() - float(p["last_fp_t"]), 3)
        audits[str(m["index"])] = ent
    if audits:
        out["audit"] = audits
    quarantined = [dict(replica=m["index"], reason=m["quarantined"])
                   for m in members if m.get("quarantined")]
    if quarantined:
        out["quarantined"] = quarantined
    client.close()
    print(json.dumps(out, sort_keys=True))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_store = sub.add_parser("store", help="run a standalone native "
                                           "store server")
    p_store.add_argument("--port", type=int, default=0,
                         help="listen port (0 = ephemeral, printed)")
    for name in ("start", "recover", "status"):
        p = sub.add_parser(name)
        p.add_argument("--store", default="",
                       help="store endpoint host:port (start only: "
                            "empty = own an in-process server)")
        p.add_argument("--namespace", default="fleet")
        if name != "status":
            p.add_argument("--backend",
                           choices=("stub", "tiny", "preset"),
                           default="tiny")
            p.add_argument("--preset", default="",
                           help="config.PRESETS name for --backend "
                                "preset (worker validates; the error "
                                "names every preset)")
            p.add_argument("--ckpt", default="",
                           help="optional Orbax params checkpoint for "
                                "--backend preset")
            p.add_argument("--autoscale", default="",
                           help="TPUNN_AUTOSCALE-grammar Helm spec "
                                "(empty = no autoscaler); on a "
                                "disaggregated fleet Helm scales each "
                                "pool on its own pressure")
            p.add_argument("--heartbeat-timeout", type=float,
                           default=5.0)
        if name == "start":
            p.add_argument("--replicas", type=int, default=2)
            p.add_argument("--fleet-prefill", type=int, default=0,
                           help="disaggregated prefill pool size "
                                "(needs --fleet-decode too); KV "
                                "handoff streams over serve/kv_wire")
            p.add_argument("--fleet-decode", type=int, default=0,
                           help="disaggregated decode pool size")
            p.add_argument("--role",
                           choices=("unified", "prefill", "decode"),
                           default="unified",
                           help="role for ALL --replicas workers "
                                "(enrolling one pool of a fleet whose "
                                "other pool runs elsewhere)")
            p.add_argument("--spawn-template", default="",
                           help="cross-host spawn command template; "
                                "{cmd} = shell-quoted worker command, "
                                "{index}/{role} available (e.g. "
                                "'ssh hostC {cmd}'); workers enroll "
                                "back through the store")
    args = ap.parse_args()
    return {"store": _cmd_store, "start": _cmd_start,
            "recover": _cmd_recover, "status": _cmd_status}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
