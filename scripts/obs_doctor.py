#!/usr/bin/env python
"""obs_doctor — cross-rank flight-recorder forensics.

A pod run died or hung and the dump triggers (progress watchdog, fatal
signal, unhandled exception, supervisor request — see
docs/observability.md "Flight recorder") left ``flight_rank<k>.json``
files next to the run's JSONL. This tool merges them, aligns the
per-rank collective streams, names the **first divergent collective**
(op + seq + step) and the **stalled rank**, classifies the failure
(hang vs crash vs graceful preemption vs straggler), surfaces any
injected TPUNN_CHAOS faults so synthetic failures can't be
misattributed, and prints per-rank step-time percentiles so a slow
rank stands out even when nothing diverged. Dumps from a serving fleet
(serve/fleet.py) additionally name the dead replica and the in-flight
requests it stranded (``--json`` carries them under ``fleet``). When
obs.xray was armed (TPUNN_XRAY=), the profiler-capture dirs that fired
before the dump ride along under ``xray_captures`` — the device trace
covering the incident window (render with scripts/obs_xray.py).

Usage:
    python scripts/obs_doctor.py RUNDIR              # globs flight_rank*.json
    python scripts/obs_doctor.py a.json b.json ...   # explicit dumps
    python scripts/obs_doctor.py RUNDIR --json       # machine-readable
    python scripts/obs_doctor.py --selftest          # synthetic hang, end to end
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import tempfile

sys.path.insert(0, ".")  # run from repo root without install

from pytorch_distributed_nn_tpu.obs import flight, forensics  # noqa: E402


def _analyze(paths_or_dir, expect_ranks: int | None, last: int,
             as_json: bool) -> int:
    dumps = forensics.load_dumps(paths_or_dir)
    if not dumps:
        # a quiet report, not a failure: monitoring wrappers run the
        # doctor before anything has crashed hard enough to dump
        print(f"no flight_rank*.json dumps found in {paths_or_dir}")
        return 0
    expected = list(range(expect_ranks)) if expect_ranks else None
    if as_json:
        cls = forensics.classify(dumps, expected)
        div = cls.divergence
        print(json.dumps({
            "classification": cls.kind,
            "stalled_ranks": cls.stalled_ranks,
            "crashed_ranks": cls.crashed_ranks,
            "missing_dumps": cls.missing_dumps,
            "detail": cls.detail,
            # injected-fault accounting (runtime/chaos.py): a nonzero
            # count flags the run as a TPUNN_CHAOS test, so automated
            # post-mortems don't page anyone over a synthetic failure
            "chaos_injected": {str(r): n
                               for r, n in cls.chaos_injected.items()},
            # online watchtower alerts that fired before the dump, with
            # their inline attribution (the alert already names the
            # suspect rank/collective/request — obs/watchtower.py)
            "alerts": {str(r): [{"kind": e.get("op"),
                                 "step": e.get("step"),
                                 "note": e.get("note")}
                                for e in d.alert_events]
                       for r, d in dumps.items() if d.alert_events},
            "divergence": None if div is None else {
                "index": div.index,
                "kind": div.kind,
                "missing_ranks": div.missing_ranks,
                "reference": div.reference(),
            },
            "stragglers": [dataclasses.asdict(r) for r in
                           forensics.straggler_report(dumps)],
            # replica-fleet lifecycle (serve/fleet.py): a failover dump
            # names the dead replica and the requests it stranded; None
            # for non-fleet runs so existing consumers see no new noise
            "fleet": forensics.fleet_summary(dumps),
            # Helm decisions (serve/autoscale.py) in the ring before
            # the dump — op is the action, the note carries reason +
            # replica trajectory; {} for runs without TPUNN_AUTOSCALE
            "autoscale": {
                str(r): [{"action": e.get("op"),
                          "note": e.get("note")}
                         for e in d.autoscale_events]
                for r, d in dumps.items() if d.autoscale_events},
            # Causeway traces (obs/trace.py) alive in each ring when
            # the dump landed — trace_id -> segment tally + legs, the
            # handle scripts/obs_trace.py pulls waterfalls by; None
            # for runs with TPUNN_TRACE unset
            "traces": forensics.trace_summary(dumps),
            # Abacus charges (obs/meter.py) in the rings — per-kind
            # billed totals + the top-billing tenant by FLOPs; None
            # for runs with TPUNN_METER unset
            "meter": forensics.meter_summary(dumps),
            # profiler captures (obs/xray.py) that fired before the
            # dump — the landing dir per rank, so a post-mortem can go
            # straight from the incident to the device trace covering
            # it; {} for runs with TPUNN_XRAY unset
            "xray_captures": {
                str(r): [e.get("note", "").rsplit(" -> ", 1)[-1]
                         for e in d.xray_events
                         if e.get("op") == "capture"]
                for r, d in dumps.items() if d.xray_events},
        }, indent=2))
    else:
        print(forensics.render_report(dumps, expected, last=last))
    return 0


def _selftest() -> int:
    """Synthesize a 3-rank hang with the REAL recorder + dump path and
    check the doctor names the stalled rank and the divergent
    collective — an end-to-end smoke with no devices and no cluster."""
    hang_at, world = 7, 3
    with tempfile.TemporaryDirectory() as d:
        for rank in range(world):
            rec = flight.FlightRecorder(capacity=256, enabled=True)
            for step in range(10):
                rec.mark_step(step)
                if step == hang_at:
                    if rank != 1:
                        # survivors enqueue the collective rank 1 never
                        # reaches, and block inside it forever
                        rec.record("collective", "all_reduce",
                                   axis="data", nbytes=4096,
                                   step=step, note="dispatch",
                                   complete=False)
                    break  # rank 1's injected stall / survivors' block
                with rec.collective("all_reduce", axis="data",
                                    nbytes=4096, step=step):
                    pass
            rec.dump("progress_watchdog" if rank == 1
                     else "supervisor:stale ranks [1]",
                     directory=d, rank=rank)
        dumps = forensics.load_dumps(d)
        assert len(dumps) == world, f"expected {world} dumps: {dumps}"
        cls = forensics.classify(dumps, list(range(world)))
        assert cls.kind == "hang", cls
        assert cls.stalled_ranks == [1], cls
        div = cls.divergence
        assert div is not None and div.missing_ranks == [1], div
        ref = div.reference()
        assert ref["op"] == "all_reduce" and ref["step"] == hang_at, ref
        report = forensics.render_report(dumps, list(range(world)))
        for needle in ("HANG", "stalled rank(s): [1]", "all_reduce",
                       f"step={hang_at}", "NEVER COMPLETED"):
            assert needle in report, (needle, report)
        print(report)
    print("\nselftest ok: hang classified, stalled rank 1 named, "
          f"divergent collective all_reduce @ step {hang_at} found")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dumps", nargs="*",
                    help="run directory containing flight_rank*.json, "
                         "or explicit dump files")
    ap.add_argument("--expect-ranks", type=int, default=None,
                    help="world size; ranks with no dump at all are "
                         "reported as crashed/missing")
    ap.add_argument("--last", type=int, default=5,
                    help="trailing events to show per rank")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable classification")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in synthetic-hang check")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.dumps:
        ap.error("give a run directory or dump files (or --selftest)")
    target = (args.dumps[0]
              if len(args.dumps) == 1 and os.path.isdir(args.dumps[0])
              else args.dumps)
    try:
        return _analyze(target, args.expect_ranks, args.last, args.json)
    except BrokenPipeError:  # `obs_doctor ... | head` is a normal use
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
