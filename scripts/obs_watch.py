#!/usr/bin/env python
"""Watchtower console: replay or tail a run's JSONL through the
online detectors and render alerts + SLO burn rates.

Two modes over the same engine (:mod:`obs.watchtower`):

- **replay** (default): read the whole metrics JSONL a train/serve run
  wrote, feed every record through :func:`watchtower.events_from_jsonl`
  in recorded order, print the alert stream and the end-state summary.
  Detectors consume event time only, so replaying the same file twice
  prints byte-identical alerts — this is the post-mortem view;
- **--follow**: tail the file live (poll for appended lines), printing
  alerts as they fire — the "watch the run" view for a job writing
  ``--metrics-out`` on the same host.

A third mode audits Helm instead of the detectors:

- **--autoscale**: shadow-replay a recorded decision journal
  (``bench.py --autoscale --autoscale-out``) through the REAL policy:
  every ``autoscale_decision`` record carries its spec, evidence, and
  pre-decision state, so :func:`serve.autoscale.replay_decision`
  re-derives the verdict standalone and any divergence from what the
  journal claims exits 1 — the "would Helm do that again?" audit.

Usage:
    python scripts/obs_watch.py runs/metrics.jsonl
    python scripts/obs_watch.py runs/metrics.jsonl --follow
    python scripts/obs_watch.py runs/metrics.jsonl \
        --spec ttft_slo_s=0.25:burn_threshold=4 --json
    python scripts/obs_watch.py runs/helm.jsonl --autoscale
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")  # run from repo root without install

from pytorch_distributed_nn_tpu.obs import watchtower  # noqa: E402
from pytorch_distributed_nn_tpu.obs.registry import (  # noqa: E402
    get_registry,
)

_SEV_MARK = {watchtower.WARN: "WARN", watchtower.PAGE: "PAGE"}


def _render_alert(a: "watchtower.Alert") -> str:
    mark = _SEV_MARK.get(a.severity, a.severity)
    line = (f"[{mark}] t={a.t:.3f} {a.kind} "
            f"(value={a.value:g} threshold={a.threshold:g}) {a.detail}")
    if a.attribution:
        keys = {k: v for k, v in a.attribution.items()
                if k != "forensics"}
        if keys:
            line += f"  attribution={json.dumps(keys, sort_keys=True)}"
    return line


def _burn_gauges() -> dict[str, float]:
    flat = get_registry().snapshot()
    return {k: v for k, v in sorted(flat.items())
            if k.startswith("watchtower_burn_rate")}


def _print_summary(tower: "watchtower.Watchtower",
                   as_json: bool) -> None:
    summary = tower.summary()
    burns = _burn_gauges()
    if as_json:
        print(json.dumps({"summary": summary, "burn_rates": burns,
                          "alerts": [a.as_dict() for a in tower.alerts]},
                         sort_keys=True))
        return
    print("\n== watchtower summary ==")
    print(f"  alerts: {summary['alerts_total']} "
          f"({summary['pages']} pages)  by kind: {summary['by_kind']}")
    if summary["burns_active"]:
        print(f"  burning SLOs: {', '.join(summary['burns_active'])}")
    if summary["drifting_ranks"]:
        print(f"  drifting ranks: {summary['drifting_ranks']}")
    for key, val in burns.items():
        print(f"  {key} = {val:g}")


def _feed(tower: "watchtower.Watchtower", line: str,
          as_json: bool) -> None:
    line = line.strip()
    if not line:
        return
    try:
        rec = json.loads(line)
    except json.JSONDecodeError:
        return  # torn tail line from a live writer
    before = len(tower.alerts)
    for ev in watchtower.events_from_jsonl(rec):
        tower.observe(ev)
    for alert in tower.alerts[before:]:
        print(alert.as_json() if as_json else _render_alert(alert))


def _shadow_replay_autoscale(path: str, as_json: bool) -> int:
    """--autoscale: re-run every journaled decision through the real
    policy and diff the verdicts. Each record is self-contained (spec
    + evidence + pre-decision state), so no fleet, tower, or ordering
    is needed — a tampered or stale journal diverges record by record."""
    from pytorch_distributed_nn_tpu.serve import autoscale

    try:
        f = open(path)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    total = diverged = 0
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line from a live writer
            if rec.get("event", "autoscale_decision") \
                    != "autoscale_decision":
                continue
            total += 1
            want = (rec.get("action"), rec.get("reason"),
                    rec.get("to_replicas"))
            try:
                got = autoscale.replay_decision(rec)
            except (KeyError, TypeError, ValueError) as e:
                got = ("unreplayable", str(e), None)
            ok = got == want
            diverged += not ok
            if as_json:
                print(json.dumps(
                    {"seq": rec.get("seq"), "t": rec.get("t"),
                     "journaled": list(want), "replayed": list(got),
                     "ok": ok}, sort_keys=True))
            elif not ok:
                print(f"DIVERGED seq={rec.get('seq')} "
                      f"t={rec.get('t')}: journal says "
                      f"{want[0]}->{want[2]} ({want[1]}), policy "
                      f"says {got[0]}->{got[2]} ({got[1]})")
    verdict = {"decisions": total, "diverged": diverged,
               "ok": diverged == 0 and total > 0}
    if as_json:
        print(json.dumps({"autoscale_shadow": verdict},
                         sort_keys=True))
    else:
        print(f"\n== autoscale shadow replay ==\n  {total} decisions "
              f"re-derived, {diverged} diverged"
              + ("" if total else " (no autoscale_decision records)"))
    return 0 if verdict["ok"] else 1


def main() -> int:
    ap = argparse.ArgumentParser(
        description="replay/tail a metrics JSONL through the watchtower")
    ap.add_argument("metrics", help="JSONL metrics file from a run")
    ap.add_argument("--spec", default="1",
                    help="TPUNN_WATCH-style detector spec "
                         "(default: the stock thresholds)")
    ap.add_argument("--follow", action="store_true",
                    help="tail the file live instead of replaying once")
    ap.add_argument("--poll-s", type=float, default=0.5,
                    help="tail poll interval with --follow")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (alert JSON lines + "
                         "one summary object)")
    ap.add_argument("--autoscale", action="store_true",
                    help="shadow-replay a recorded Helm decision "
                         "journal through the real policy; exit 1 on "
                         "any divergence")
    args = ap.parse_args()

    if args.autoscale:
        return _shadow_replay_autoscale(args.metrics, args.json)

    tower = watchtower.Watchtower(watchtower.parse_spec(args.spec),
                                  dump_on_page=False)
    try:
        f = open(args.metrics)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    with f:
        for line in f:
            _feed(tower, line, args.json)
        if args.follow:
            try:
                while True:
                    line = f.readline()
                    if line:
                        _feed(tower, line, args.json)
                    else:
                        time.sleep(args.poll_s)
            except KeyboardInterrupt:
                pass
    _print_summary(tower, args.json)
    return 1 if tower.summary()["pages"] else 0


if __name__ == "__main__":
    sys.exit(main())
