#!/usr/bin/env python
"""Render obs.xray capture summaries: per-op attribution + compile tally.

Each anomaly-triggered capture (armed via ``TPUNN_XRAY=``) lands as an
``xray_<rank>_<nn>_<reason>/xray_summary.json`` directory next to the
flight-ring dump. This script finds every capture under a directory and
prints, per capture:

- the trigger (reason, step, wall window) and whether the device
  profiler ran or the flight ring was the only source;
- the per-op table: time share, calls, bytes, and — when the engine had
  cost context — FLOPs, achieved FLOP/s and roofline fraction per
  compute op, with collectives cross-checked against the recorded wire
  bytes;
- the compile tally observed during the capture window.

Usage:
    python scripts/obs_xray.py [dir]            # default: flight dump dir
    python scripts/obs_xray.py runs/obs --json  # machine-readable
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, ".")  # run from repo root without install

from pytorch_distributed_nn_tpu.obs import flight, xray  # noqa: E402


def print_capture(path: str, summary: dict, *, top: int) -> None:
    att = summary.get("attribution") or {}
    print(f"== xray capture: {summary.get('dir', path)} ==")
    print(f"  trigger: {summary.get('reason', '?')} at step "
          f"{summary.get('trigger_step', -1)}  "
          f"({summary.get('steps', 0)} step(s), "
          f"{max(float(summary.get('t_end', 0.0)) - float(summary.get('t_start', 0.0)), 0.0):.3f}s wall, "
          f"profiler={'on' if summary.get('profiler') else 'off'}, "
          f"source={att.get('source', 'none')})")
    compiles = summary.get("compiles") or {}
    if compiles:
        total = sum(compiles.values())
        secs = float(summary.get("compile_seconds", 0.0))
        names = ", ".join(f"{k}×{v}" for k, v in
                          sorted(compiles.items(), key=lambda kv: -kv[1]))
        print(f"  compiles in window: {total} ({secs:.2f}s): {names}")
    table = xray.render_op_table(att, top=top)
    if table:
        print("  " + table.replace("\n", "\n  "))
    comm = att.get("comm") or {}
    if comm.get("ring_vs_recorder") is not None:
        print(f"  wire-byte cross-check: ring/recorder = "
              f"{comm['ring_vs_recorder']:.3f} "
              f"(ring {comm.get('ring_nbytes', 0) / 1e6:.2f} MB vs "
              f"recorder {comm.get('expected_wire_bytes', 0) / 1e6:.2f} "
              f"MB over the window)")
    print()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dir", nargs="?", default="",
                    help="directory holding xray_*/xray_summary.json "
                         "(default: the flight dump dir — "
                         "TPUNN_FLIGHT_DIR or the tmp fallback)")
    ap.add_argument("--top", type=int, default=12,
                    help="rows to show per per-op table")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object per capture instead of "
                         "tables")
    args = ap.parse_args(argv)
    directory = args.dir or flight.resolve_dump_dir()
    paths = xray.find_captures(directory)
    if not paths:
        print(f"no xray captures under {directory}", file=sys.stderr)
        return 1
    for p in paths:
        try:
            summary = xray.load_capture(p)
        except (OSError, json.JSONDecodeError) as e:
            print(f"unreadable capture {p}: {e}", file=sys.stderr)
            continue
        if args.json:
            print(json.dumps({"path": p, **summary}, sort_keys=True))
        else:
            print_capture(p, summary, top=args.top)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
