#!/usr/bin/env python
"""Metric-name inventory: enumerate every instrument the codebase
registers and diff against the documented table.

Every counter/gauge/histogram the stack registers goes through the
:mod:`obs.registry` get-or-create factories, so the full inventory is
enumerable statically: walk the package AST for
``.counter("name", ...)`` / ``.gauge("name", ...)`` /
``.histogram("name", ...)`` calls with a literal name (importing the
world would need an accelerator and only registers what that process
touches; the AST sees every call site).

``--check`` diffs that inventory against the "Metric inventory" table
in ``docs/observability.md`` and exits non-zero on drift in either
direction — an undocumented metric (someone added an instrument and
skipped the docs) or a stale doc row (the instrument went away). Wired
into tier-1 (tests/test_quality.py), so e.g. ``skyline_offered_rps``
cannot land without its table row.

Usage:
    python scripts/obs_metrics.py --list
    python scripts/obs_metrics.py --check
"""

from __future__ import annotations

import argparse
import ast
import pathlib
import re
import sys

sys.path.insert(0, ".")  # run from repo root without install

REPO = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = REPO / "pytorch_distributed_nn_tpu"
DOC = REPO / "docs" / "observability.md"
_FACTORIES = ("counter", "gauge", "histogram")


def registered_metrics(package: pathlib.Path = PACKAGE) -> dict:
    """name -> {kind, files} from every literal registration call
    site; dynamic (non-literal) names land under the "" key so the
    checker can say how many it could not follow."""
    out: dict[str, dict] = {}
    dynamic = 0
    for path in sorted(package.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError as e:  # a broken file fails the lint loudly
            raise SystemExit(f"obs_metrics: cannot parse {path}: {e}")
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _FACTORIES
                    and node.args):
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                dynamic += 1
                continue
            name = first.value
            rel = str(path.relative_to(REPO))
            entry = out.setdefault(
                name, {"kind": node.func.attr, "files": []})
            if rel not in entry["files"]:
                entry["files"].append(rel)
    if dynamic:
        out[""] = {"kind": "dynamic", "files": [],
                   "count": dynamic}
    return out


_ROW = re.compile(r"^\|\s*`([a-zA-Z_][a-zA-Z0-9_]*)(?:\{[^`]*\})?`")


def documented_metrics(doc: pathlib.Path = DOC) -> set[str]:
    """Metric names from the docs table: rows of the "Metric
    inventory" section whose first cell is a backticked name
    (an optional ``{label,...}`` suffix is part of the cell, not the
    name)."""
    names: set[str] = set()
    in_section = False
    for line in doc.read_text().splitlines():
        if line.startswith("#"):
            in_section = "metric inventory" in line.lower()
            continue
        if not in_section:
            continue
        m = _ROW.match(line.strip())
        if m and m.group(1) not in ("metric",):
            names.add(m.group(1))
    return names


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--list", action="store_true",
                    help="print the registered-metric inventory")
    ap.add_argument("--check", action="store_true",
                    help="diff inventory vs docs/observability.md "
                         "'Metric inventory' table; rc=1 on drift")
    args = ap.parse_args(argv)
    reg = registered_metrics()
    dynamic = reg.pop("", None)
    if args.list or not args.check:
        for name in sorted(reg):
            entry = reg[name]
            print(f"{entry['kind']:>9}  {name:<40} "
                  f"{', '.join(entry['files'])}")
        if dynamic:
            print(f"(+{dynamic['count']} dynamic-name registration(s) "
                  f"not statically enumerable)")
        if not args.check:
            return 0
    documented = documented_metrics()
    undocumented = sorted(set(reg) - documented)
    stale = sorted(documented - set(reg))
    ok = True
    if undocumented:
        ok = False
        print("UNDOCUMENTED metrics (add rows to the 'Metric "
              "inventory' table in docs/observability.md):")
        for name in undocumented:
            print(f"  {name}  ({', '.join(reg[name]['files'])})")
    if stale:
        ok = False
        print("STALE doc rows (no such registration in the package):")
        for name in stale:
            print(f"  {name}")
    if ok:
        print(f"metric inventory ok: {len(reg)} registered, "
              f"{len(documented)} documented")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
