#!/bin/bash
# Poll the axon TPU tunnel until it answers, then exit 0.
# Logs every attempt to scripts/tunnel_probe.log.
LOG=/root/repo/scripts/tunnel_probe.log
for i in $(seq 1 200); do
  echo "[$(date -u +%FT%TZ)] probe $i" >> "$LOG"
  if timeout 90 python -c "import jax; d=jax.devices(); assert d and d[0].platform=='tpu'; print(d)" >> "$LOG" 2>&1; then
    echo "[$(date -u +%FT%TZ)] TUNNEL UP" >> "$LOG"
    exit 0
  fi
  echo "[$(date -u +%FT%TZ)] down (rc=$?)" >> "$LOG"
  sleep 480
done
echo "[$(date -u +%FT%TZ)] gave up after 200 probes" >> "$LOG"
exit 1
