#!/usr/bin/env python
"""Async parameter-server training entrypoint — the reference's PS
trainer script (SURVEY.md §2a "Parameter-server / async trainer": rank 0
holds params, workers send grads / recv params). Process-level async —
see pytorch_distributed_nn_tpu.parallel.ps for the design.

Usage:
    python scripts/train_ps.py --preset mlp_mnist --workers 2 --steps 60
"""

from __future__ import annotations

import argparse
import logging
import sys

sys.path.insert(0, ".")

from pytorch_distributed_nn_tpu.runtime.platform import (
    apply_platform_overrides,
)

apply_platform_overrides()

import jax
import jax.numpy as jnp

from pytorch_distributed_nn_tpu.config import get_config
from pytorch_distributed_nn_tpu.data import get_dataset
from pytorch_distributed_nn_tpu.models import get_model
from pytorch_distributed_nn_tpu.parallel import ps
from pytorch_distributed_nn_tpu.train.losses import get_loss_fn
from pytorch_distributed_nn_tpu.train.optim import make_optimizer


def main(argv: list[str]) -> int:
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="mlp_mnist")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--steps", type=int, default=60,
                    help="total gradient pushes across workers")
    ap.add_argument("--max-staleness", type=int, default=8)
    args = ap.parse_args(argv)

    cfg = get_config(args.preset)
    dataset = get_dataset(cfg.data.dataset, seed=cfg.seed,
                          batch_size=cfg.data.batch_size,
                          seq_len=cfg.data.seq_len,
                          vocab_size=cfg.data.vocab_size,
                          path=cfg.data.path,
                          token_dtype=cfg.data.token_dtype,
                          sample=cfg.data.sample,
                          holdout_frac=cfg.data.holdout_frac,
                          image_size=cfg.data.image_size)
    model = get_model(cfg.model)
    loss_fn = get_loss_fn(cfg.data.dataset)
    x0, _ = dataset.batch(0)
    params = model.init(jax.random.key(cfg.seed), jnp.asarray(x0[:1]),
                        train=False)["params"]
    tx = make_optimizer(cfg.optim, total_steps=args.steps)

    def loss_of(params, x, y):
        logits = model.apply({"params": params}, x, train=False)
        return loss_fn(logits, y)

    grad_fn = jax.jit(jax.grad(loss_of))

    per_worker = args.steps // args.workers
    worker_batches = [
        [tuple(map(jnp.asarray, dataset.batch(w * per_worker + i)))
         for i in range(per_worker)]
        for w in range(args.workers)
    ]
    final_params, applied = ps.run_ps_local(params, tx, grad_fn,
                                            worker_batches)
    x, y = map(jnp.asarray, dataset.batch(10_000))
    final_loss = float(loss_of(final_params, x, y))
    print(f"ps: applied {applied} grads from {args.workers} workers, "
          f"held-out loss {final_loss:.4f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
