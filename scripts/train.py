#!/usr/bin/env python
"""Training entrypoint — the reference's ``train.py`` (SURVEY.md §1
Launch/Entrypoints rows), TPU-native.

Usage:
    python scripts/train.py --preset mlp_mnist [--steps 100]
        [--optim.lr 0.05] [--parallel.strategy dp_explicit] ...

Multi-host: launch one process per host with RANK/WORLD_SIZE/MASTER_ADDR
(torch-style) or COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID env vars;
see pytorch_distributed_nn_tpu.runtime.bootstrap.
"""

from __future__ import annotations

import logging
import sys

sys.path.insert(0, ".")  # run from repo root without install

from pytorch_distributed_nn_tpu.runtime.platform import (
    apply_platform_overrides,
)

apply_platform_overrides()  # honor JAX_PLATFORMS before first backend use

from pytorch_distributed_nn_tpu.config import get_config, parse_overrides
from pytorch_distributed_nn_tpu.runtime import bootstrap
from pytorch_distributed_nn_tpu.train.trainer import Trainer


def main(argv: list[str]) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    overrides = parse_overrides(argv)
    preset = overrides.pop("preset", "mlp_mnist")
    info = bootstrap.initialize()
    cfg = get_config(preset, **overrides)
    # context manager: closes the metrics JSONL handle and drains async
    # checkpoint writes even when train() raises
    with Trainer(cfg) as trainer:
        history = trainer.train()
    if info.is_coordinator and history:
        final = history[-1]
        print(f"final: step={final.step} loss={final.loss:.4f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
