#!/usr/bin/env python
"""Serving entrypoint: continuous-batching engine + synthetic load.

Usage:
    python scripts/serve.py --preset llama3_longcontext \
        [--checkpoint-dir runs/ckpt] [--slots 4] [--max-seq-len 256] \
        [--requests 32] [--rate 20] [--max-new 16] \
        [--closed-loop] [--users 4] [--metrics-out serve.jsonl]

Runs the loopback server (serve/server.py) against a synthetic ragged
workload and prints one JSON summary line (requests, rejects,
tokens/s, TTFT and per-token latency percentiles, batch occupancy, KV
utilization). Without --checkpoint-dir the model is randomly
initialized — the scheduler/latency behavior under test does not
depend on the weights.

SIGTERM drains gracefully: queued requests are rejected, in-flight
sequences finish, and the process exits GRACEFUL_EXIT_CODE (83) so an
agent classifies the shutdown like a trainer preemption. Load-shed
drills: TPUNN_CHAOS='serve_reject@p=0.3' (docs/serving.md).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")  # run from repo root without install

from pytorch_distributed_nn_tpu.runtime.platform import (
    apply_platform_overrides,
)

apply_platform_overrides()

import jax
import jax.numpy as jnp
import numpy as np


from pytorch_distributed_nn_tpu.obs.stats import percentile


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", default="llama3_longcontext")
    ap.add_argument("--checkpoint-dir", default="")
    ap.add_argument("--slots", type=int, default=4,
                    help="decode batch slots (concurrent sequences)")
    ap.add_argument("--max-seq-len", type=int, default=256)
    ap.add_argument("--block-size", type=int, default=16,
                    help="KV pool block size in tokens")
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--max-prefills", type=int, default=2,
                    help="admissions per decode round")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--rate", type=float, default=20.0,
                    help="open-loop arrival rate, req/s")
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--min-prompt", type=int, default=4)
    ap.add_argument("--max-prompt", type=int, default=48)
    ap.add_argument("--deadline", type=float, default=0.0,
                    help="per-request queue deadline in seconds "
                         "(0 = none)")
    ap.add_argument("--closed-loop", action="store_true",
                    help="closed-loop clients instead of open-loop")
    ap.add_argument("--users", type=int, default=4,
                    help="closed-loop user count")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--metrics-out", default="",
                    help="JSONL path for serve_request/serve_summary "
                         "events (scripts/obs_report.py reads these)")
    args, rest = ap.parse_known_args(argv)

    from pytorch_distributed_nn_tpu.config import (
        get_config,
        parse_overrides,
    )
    from pytorch_distributed_nn_tpu.models import get_model
    from pytorch_distributed_nn_tpu.obs import audit, meter, trace, watchtower
    from pytorch_distributed_nn_tpu.runtime import chaos
    from pytorch_distributed_nn_tpu.runtime.failure import (
        GRACEFUL_EXIT_CODE,
    )
    from pytorch_distributed_nn_tpu.serve import (
        InferenceServer,
        ServingEngine,
        closed_loop_client,
        install_sigterm_drain,
        open_loop_client,
        ragged_prompt_sampler,
    )
    from pytorch_distributed_nn_tpu.utils.metrics import MetricsLogger

    install_sigterm_drain()

    cfg = get_config(args.preset, **parse_overrides(rest))
    model = get_model(cfg.model)
    if args.checkpoint_dir:
        cfg.checkpoint_dir = args.checkpoint_dir
        cfg.steps = 0
        from pytorch_distributed_nn_tpu.train.trainer import Trainer

        trainer = Trainer(cfg)
        if trainer.ckpt is None or trainer.ckpt.latest_step() is None:
            print(f"no checkpoint found in {args.checkpoint_dir}",
                  file=sys.stderr)
            return 1
        params = jax.device_get(trainer.state.params)
        trainer.close()
    else:
        print("[serve] no --checkpoint-dir: random init (load test "
              "only)", file=sys.stderr)
        params = model.init(
            jax.random.key(cfg.seed),
            jnp.zeros((1, 8), jnp.int32), train=False,
        )["params"]

    # no --metrics-out: keep stdout to the single summary line below
    metrics = MetricsLogger(args.metrics_out) if args.metrics_out else None
    engine = ServingEngine(
        model, params, max_slots=args.slots,
        max_seq_len=args.max_seq_len, block_size=args.block_size,
        max_queue=args.max_queue,
        max_prefills_per_round=args.max_prefills, metrics=metrics,
    )
    vocab = getattr(model, "vocab_size", 1000)
    max_prompt = max(args.min_prompt,
                     min(args.max_prompt,
                         args.max_seq_len - args.max_new))
    sampler = ragged_prompt_sampler(
        vocab, min_len=args.min_prompt, max_len=max_prompt,
        seed=args.seed)

    server = InferenceServer(engine).start()
    # Warm the compile caches (every prefill pad bucket the sampler can
    # hit, plus the decode step) so TTFT measures serving, not XLA.
    from pytorch_distributed_nn_tpu.serve.engine import _bucket_len

    warm_rng = np.random.default_rng(args.seed)
    b = _bucket_len(args.min_prompt)
    top = min(_bucket_len(max_prompt), args.max_seq_len)
    while b <= top:
        # max_new=2 forces one decode round: the prefill-produced first
        # token alone would retire the row before _serve_step compiles
        L = min(b, args.max_seq_len - 2)
        server.generate(
            warm_rng.integers(0, vocab, size=(L,)).astype(np.int32), 2)
        b *= 2
    warm_done = len(engine.completed)
    warm_rounds = len(engine.round_seconds)
    # armed after warmup so a serve_reject@ drill can't shed the
    # compile-cache warm requests and pollute the timed TTFTs — and so
    # the watchtower's TTFT burn-rate window never sees compile time
    chaos.maybe_init()
    watchtower.maybe_init(metrics=metrics)
    trace.maybe_init(metrics=metrics)  # TPUNN_TRACE — Causeway
    meter.maybe_init(metrics=metrics)  # TPUNN_METER — Abacus
    audit.maybe_init(metrics=metrics)  # TPUNN_AUDIT — Lighthouse
    t0 = time.monotonic()
    try:
        if args.closed_loop:
            per_user = max(args.requests // max(args.users, 1), 1)
            reqs = closed_loop_client(
                server, num_users=args.users,
                requests_per_user=per_user,
                max_new_tokens=args.max_new, prompt_sampler=sampler)
        else:
            reqs = open_loop_client(
                server, num_requests=args.requests, rate_hz=args.rate,
                max_new_tokens=args.max_new, prompt_sampler=sampler,
                deadline_s=args.deadline or None)
    finally:
        server.stop()
    wall = time.monotonic() - t0

    done = [r for r in reqs if r.ok]
    rejects: dict[str, int] = {}
    for r in reqs:
        if r.state == "rejected":
            rejects[r.reject_reason] = rejects.get(r.reject_reason, 0) + 1
    timed = engine.completed[warm_done:]  # warmup excluded
    ttfts = [c["ttft_s"] for c in timed]
    tok_lat = engine.round_seconds[warm_rounds:]
    summary = dict(
        requests=len(reqs), completed=len(done),
        rejected=sum(rejects.values()), reject_reasons=rejects,
        preempted=server.preempted,
        wall_s=round(wall, 3),
        tokens_out=int(sum(c["new_tokens"] for c in timed)),
        tokens_per_s=round(
            sum(c["new_tokens"] for c in timed) / max(wall, 1e-9), 2),
        ttft_p50_s=percentile(ttfts, 0.50),
        ttft_p95_s=percentile(ttfts, 0.95),
        token_lat_p50_s=percentile(tok_lat, 0.50),
        token_lat_p95_s=percentile(tok_lat, 0.95),
        token_lat_p99_s=percentile(tok_lat, 0.99),
        **{k: v for k, v in engine.summary().items()
           if k in ("rounds", "occupancy", "kv_util")},
    )
    if metrics is not None:
        metrics.emit("serve_summary", **summary)
        metrics.close()
    print(json.dumps(summary))
    if server.preempted:
        return GRACEFUL_EXIT_CODE
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
