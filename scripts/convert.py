#!/usr/bin/env python
"""Checkpoint conversion: torch/HF weights ↔ framework checkpoints.

Import (torch → here): load a ``torch.save``'d state_dict (torch pickle
zip via ``torch.load(weights_only=True)``) or an HF ``.safetensors``
file (via ``safetensors.torch``), map it onto the preset's model via
utils/torch_interop, and write a framework checkpoint that
``scripts/train.py --resume`` / ``scripts/generate.py
--checkpoint-dir`` consume directly:

    python scripts/convert.py --arch llama3 --preset llama3_8b_zero \
        --torch-checkpoint llama.pt --out runs/llama_ckpt \
        --model.extra '{"num_layers":2,"d_model":64,...}'

Export (here → torch): read the latest framework checkpoint and write an
HF-layout state_dict torch can load:

    python scripts/convert.py --arch llama3 --preset llama3_8b_zero \
        --export runs/llama_ckpt --torch-checkpoint out.pt ...

The model dims must match the weights being converted — set them via
``--model.extra`` exactly as for training (a mismatch fails with the
offending shapes, nothing half-loads).
"""

from __future__ import annotations

import argparse
import sys

sys.path.insert(0, ".")  # run from repo root without install

from pytorch_distributed_nn_tpu.runtime.platform import (
    apply_platform_overrides,
)

apply_platform_overrides()


def _load_state_dict(path: str):
    if str(path).endswith(".safetensors"):
        from safetensors.torch import load_file

        return load_file(path)
    import torch

    return torch.load(path, map_location="cpu", weights_only=True)


def _converted_params(arch: str, state_dict, model_cfg):
    """Returns (params, model_state_or_None) — model_state carries the
    non-param variable collections (ResNet BatchNorm running stats)."""
    from pytorch_distributed_nn_tpu.utils import torch_interop as ti

    e = model_cfg.extra
    if arch == "llama3":
        return ti.llama_params_from_torch(
            state_dict,
            num_layers=e.get("num_layers", 32),
            num_heads=e.get("num_heads", 32),
            num_kv_heads=e.get("num_kv_heads", 8),
        ), None
    if arch == "bert":
        return ti.bert_params_from_torch(
            state_dict,
            num_layers=e.get("num_layers", 12),
            num_heads=e.get("num_heads", 12),
        ), None
    if arch == "gpt2":
        return ti.gpt2_params_from_torch(
            state_dict,
            num_layers=e.get("num_layers", 12),
            num_heads=e.get("num_heads", 12),
        ), None
    if arch == "resnet50":
        return ti.resnet50_params_from_torch(
            state_dict,
            stage_sizes=tuple(e.get("stage_sizes", (3, 4, 6, 3))),
            stem=e.get("stem", "conv7"),
        )
    if arch == "vit":
        return ti.vit_params_from_torch(
            state_dict,
            num_layers=e.get("num_layers", 6),
            num_heads=e.get("num_heads", 3),
        ), None
    if arch == "lenet":
        return ti.lenet_params_from_torch(state_dict), None
    if arch == "mlp":
        return ti.mlp_params_from_torch(state_dict), None
    raise ValueError(
        f"unknown --arch {arch!r} (llama3 | bert | gpt2 | resnet50 | "
        "vit | lenet | mlp)"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--arch", required=True,
                    choices=("llama3", "bert", "gpt2", "resnet50",
                             "vit", "lenet", "mlp"))
    ap.add_argument("--preset", required=True)
    ap.add_argument("--torch-checkpoint", required=True,
                    help="torch state_dict file (read on import, "
                         "written on export)")
    ap.add_argument("--out", default="",
                    help="framework checkpoint dir to write (import mode)")
    ap.add_argument("--export", default="",
                    help="framework checkpoint dir to read (export mode)")
    args, rest = ap.parse_known_args(argv)
    if bool(args.out) == bool(args.export):
        ap.error("exactly one of --out (import) / --export is required")

    import jax
    import numpy as np
    import torch

    from pytorch_distributed_nn_tpu.config import get_config, parse_overrides
    from pytorch_distributed_nn_tpu.train.checkpoint import CheckpointManager
    from pytorch_distributed_nn_tpu.train.trainer import Trainer

    cfg = get_config(args.preset, **parse_overrides(rest))
    cfg.steps = 0
    cfg.checkpoint_dir = ""  # Trainer must not auto-resume anything
    # Norm epsilons need no special handling: the model builders default
    # to the HF-conventional values (bert 1e-12, gpt2 1e-5, llama3
    # 1e-5), so every consumer of the converted checkpoint — convert,
    # eval, generate, resume — reconstructs the same model. Checkpoints
    # trained with nonstandard eps still need --model.extra everywhere.
    trainer = Trainer(cfg)

    if args.out:
        state_dict = _load_state_dict(args.torch_checkpoint)
        converted, model_state = _converted_params(args.arch, state_dict,
                                                   cfg.model)
        if cfg.parallel.strategy == "pipeline":
            # pipeline checkpoints hold STACKED stage params — restack
            # the flat converted tree so train.py --resume consumes it
            from pytorch_distributed_nn_tpu.parallel.pipeline import (
                partition_for,
                stack_stage_params,
            )

            interleaved = (cfg.parallel.pipeline_schedule
                           == "interleaved")
            converted = stack_stage_params(
                converted, partition_for(trainer.model),
                max(cfg.mesh.pipe, 1),
                n_chunks=(max(cfg.parallel.pipe_chunks, 1)
                          if interleaved else 1),
                chunked=interleaved,
            )
        from pytorch_distributed_nn_tpu.runtime.mesh import place_like

        try:
            placed = place_like(converted, trainer.state.params)
            state = trainer.state.replace(params=placed)
            if model_state is not None:  # e.g. BatchNorm running stats
                state = state.replace(model_state=place_like(
                    model_state, trainer.state.model_state))
        except ValueError as e:
            raise SystemExit(
                f"converted weights do not fit the configured model "
                f"(set --model.extra to the checkpoint's dims): {e}"
            ) from e
        mgr = CheckpointManager(args.out, async_save=False)
        mgr.save(state, data_step=0,
                 extra_meta={"converted_from": args.torch_checkpoint},
                 force=True)
        mgr.close()
        print(f"wrote framework checkpoint: {args.out} "
              f"(step 0, arch {args.arch})")
        return 0

    mgr = CheckpointManager(args.export, async_save=False)
    state, meta = mgr.restore(trainer.state)
    mgr.close()
    exporters = ("llama3", "bert", "gpt2", "vit", "resnet50")
    if args.arch not in exporters:
        raise SystemExit(
            f"export supports --arch {' | '.join(exporters)}"
        )
    from pytorch_distributed_nn_tpu.utils import torch_interop as ti

    params = state.params
    if cfg.parallel.strategy == "pipeline":
        from pytorch_distributed_nn_tpu.parallel.pipeline import (
            partition_for,
            unstack_stage_params,
        )

        interleaved = cfg.parallel.pipeline_schedule == "interleaved"
        params = unstack_stage_params(
            jax.device_get(params), partition_for(trainer.model),
            n_chunks=(max(cfg.parallel.pipe_chunks, 1)
                      if interleaved else 1),
            chunked=interleaved,
        )
    host_params = jax.tree.map(
        lambda x: np.asarray(jax.device_get(x), np.float32), params
    )
    if args.arch == "resnet50":
        host_stats = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x), np.float32),
            dict(state.model_state),
        )
        sd = ti.resnet50_params_to_torch(
            host_params, host_stats,
            stage_sizes=tuple(cfg.model.extra.get("stage_sizes",
                                                  (3, 4, 6, 3))),
        )
    else:
        sd = {
            "llama3": ti.llama_params_to_torch,
            "bert": ti.bert_params_to_torch,
            "gpt2": ti.gpt2_params_to_torch,
            "vit": ti.vit_params_to_torch,
        }[args.arch](host_params)
    torch.save(sd, args.torch_checkpoint)
    print(f"wrote torch state_dict: {args.torch_checkpoint} "
          f"(from step {meta['step']})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
