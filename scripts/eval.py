#!/usr/bin/env python
"""Evaluation entrypoint: checkpoint -> held-out loss/accuracy.

Usage:
    python scripts/eval.py --preset mlp_mnist --checkpoint-dir runs/ckpt \
        [--batches 16] [--a.b config overrides ...]

Restores the latest checkpoint into the preset's model and runs the
held-out evaluation stream (same-task batches from a step range training
cannot reach — train/trainer.py). Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, ".")  # run from repo root without install

from pytorch_distributed_nn_tpu.runtime.platform import (
    apply_platform_overrides,
)

apply_platform_overrides()


def _restore_pipeline_params(cfg, checkpoint_dir):
    """Stacked pipeline checkpoint → flat (unstacked) param tree on
    host, or None if no checkpoint exists."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_nn_tpu.data import get_dataset
    from pytorch_distributed_nn_tpu.models import get_model
    from pytorch_distributed_nn_tpu.parallel.pipeline import (
        partition_for,
        stack_stage_params,
        unstack_stage_params,
    )
    from pytorch_distributed_nn_tpu.train.checkpoint import (
        CheckpointManager,
    )
    from pytorch_distributed_nn_tpu.train.optim import make_optimizer
    from pytorch_distributed_nn_tpu.train.state import TrainState

    mgr = CheckpointManager(checkpoint_dir, async_save=False)
    if mgr.latest_step() is None:
        mgr.close()
        return None
    model = get_model(cfg.model)
    ds = get_dataset(cfg.data.dataset, seed=cfg.seed, batch_size=1,
                     seq_len=cfg.data.seq_len,
                     vocab_size=cfg.data.vocab_size)
    x0, _ = ds.batch(0)
    flat = model.init(jax.random.key(cfg.seed), jnp.asarray(x0),
                      train=False)["params"]
    part = partition_for(model)
    n_stages = max(cfg.mesh.pipe, 1)
    stacked = stack_stage_params(flat, part, n_stages)
    template = TrainState.create(
        apply_fn=model.apply, params=stacked,
        tx=make_optimizer(cfg.optim, total_steps=max(cfg.steps, 1)),
        rng=jax.random.key(cfg.seed + 1),
    )
    state, _ = mgr.restore(template)
    mgr.close()
    return unstack_stage_params(jax.device_get(state.params), part)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", required=True)
    ap.add_argument("--checkpoint-dir", required=True)
    ap.add_argument("--batches", type=int, default=16)
    args, rest = ap.parse_known_args(argv)

    import jax
    import numpy as np

    from pytorch_distributed_nn_tpu.config import get_config, parse_overrides
    from pytorch_distributed_nn_tpu.runtime import bootstrap
    from pytorch_distributed_nn_tpu.runtime.mesh import MeshSpec
    from pytorch_distributed_nn_tpu.train.trainer import Trainer

    bootstrap.initialize()
    cfg = get_config(args.preset, **parse_overrides(rest))
    cfg.steps = 0  # restore only; no training

    pipeline_params = None
    if cfg.parallel.strategy == "pipeline":
        # Pipeline checkpoints hold STACKED stage params ({'stages',
        # 'rest'}); evaluate() needs the flat tree. Restore against a
        # stacked template built from a fresh init (no pipeline mesh
        # needed — restore places to the template's single-device
        # layout), unstack, and evaluate under plain dp.
        pipeline_params = _restore_pipeline_params(
            cfg, args.checkpoint_dir
        )
        if pipeline_params is None:
            print(f"no checkpoint found in {args.checkpoint_dir}",
                  file=sys.stderr)
            return 1
        cfg.parallel.strategy = "dp"
        cfg.mesh = MeshSpec()  # drop the pipe axis for eval
        cfg.checkpoint_dir = ""
    else:
        cfg.checkpoint_dir = args.checkpoint_dir
        cfg.resume = True

    trainer = Trainer(cfg)
    if pipeline_params is not None:
        placed = jax.tree.map(
            lambda a, t: jax.device_put(
                np.asarray(a, dtype=t.dtype), t.sharding),
            pipeline_params, trainer.state.params,
        )
        trainer.state = trainer.state.replace(params=placed)
    elif trainer.ckpt is None or trainer.ckpt.latest_step() is None:
        print(f"no checkpoint found in {args.checkpoint_dir}",
              file=sys.stderr)
        return 1
    rec = trainer.evaluate(num_batches=args.batches)
    trainer.close()
    print(json.dumps(dict(step=rec.step, eval_loss=round(rec.loss, 6),
                          eval_accuracy=round(rec.accuracy, 6),
                          batches=args.batches)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
