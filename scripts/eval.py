#!/usr/bin/env python
"""Evaluation entrypoint: checkpoint -> held-out loss/accuracy.

Usage:
    python scripts/eval.py --preset mlp_mnist --checkpoint-dir runs/ckpt \
        [--batches 16] [--a.b config overrides ...]

Restores the latest checkpoint into the preset's model and runs the
held-out evaluation stream (same-task batches from a step range training
cannot reach — train/trainer.py). Prints one JSON line.

NOTE: for token_file/array_file datasets the eval stream is IN-SAMPLE
(drawn from the training rows/tokens) unless the run set
``--data.holdout_frac`` > 0 to reserve a true held-out split — use the
same value here that training used, or the "held-out" rows were trained
on. Synthetic streams are infinite and always genuinely held out.
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, ".")  # run from repo root without install

from pytorch_distributed_nn_tpu.runtime.platform import (
    apply_platform_overrides,
)

apply_platform_overrides()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", required=True)
    ap.add_argument("--checkpoint-dir", required=True)
    ap.add_argument("--batches", type=int, default=16)
    args, rest = ap.parse_known_args(argv)

    from pytorch_distributed_nn_tpu.config import get_config, parse_overrides
    from pytorch_distributed_nn_tpu.runtime import bootstrap
    from pytorch_distributed_nn_tpu.runtime.mesh import MeshSpec, place_like
    from pytorch_distributed_nn_tpu.train.trainer import Trainer

    bootstrap.initialize()
    cfg = get_config(args.preset, **parse_overrides(rest))
    cfg.steps = 0  # restore only; no training

    pipeline_params = None
    if cfg.parallel.strategy == "pipeline":
        # Pipeline checkpoints hold STACKED stage params ({'stages',
        # 'rest'}); evaluate() needs the flat tree. Restore against a
        # stacked template built from a fresh init (no pipeline mesh
        # needed — restore places to the template's single-device
        # layout), unstack, and evaluate under plain dp.
        from pytorch_distributed_nn_tpu.parallel.pipeline import (
            restore_unstacked_params,
        )

        pipeline_params = restore_unstacked_params(
            cfg, args.checkpoint_dir
        )
        if pipeline_params is None:
            print(f"no checkpoint found in {args.checkpoint_dir}",
                  file=sys.stderr)
            return 1
        cfg.parallel.strategy = "dp"
        cfg.mesh = MeshSpec()  # drop the pipe axis for eval
        cfg.checkpoint_dir = ""
    else:
        cfg.checkpoint_dir = args.checkpoint_dir
        cfg.resume = True

    trainer = Trainer(cfg)
    if pipeline_params is not None:
        trainer.state = trainer.state.replace(
            params=place_like(pipeline_params, trainer.state.params)
        )
    elif trainer.ckpt is None or trainer.ckpt.latest_step() is None:
        print(f"no checkpoint found in {args.checkpoint_dir}",
              file=sys.stderr)
        return 1
    rec = trainer.evaluate(num_batches=args.batches)
    trainer.close()
    print(json.dumps(dict(step=rec.step, eval_loss=round(rec.loss, 6),
                          eval_accuracy=round(rec.accuracy, 6),
                          batches=args.batches)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
