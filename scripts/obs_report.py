#!/usr/bin/env python
"""Render the obs telemetry stream: step-time goodput breakdown + comms.

Reads the JSONL metrics file a training run wrote
(``TrainConfig.metrics_path`` — ``train_step`` / ``goodput`` /
``goodput_summary`` / ``eval`` events) and prints:

- the per-phase goodput table (seconds and share of wall, per logged
  window and whole-run);
- the comms cross-check: recorded wire bytes per step
  (ops/collectives.CommRecorder, carried in the goodput events) against
  trace-derived collective seconds when an xprof trace dir is given
  (``--trace``), yielding implied bus bandwidth;
- the train/eval metric tail.

- the xray capture section (``--xray DIR``): per-op attribution tables
  from any anomaly-triggered ``obs.xray`` captures under that
  directory (see ``scripts/obs_xray.py`` for the standalone renderer).

Usage:
    python scripts/obs_report.py runs/metrics.jsonl [--trace runs/xprof]
        [--xray runs/obs] [--last N]
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, ".")  # run from repo root without install

from pytorch_distributed_nn_tpu.obs.stats import percentile  # noqa: E402

PHASES = ("data", "compute", "collective", "checkpoint", "eval", "other")


def load_events(path: str) -> list[dict]:
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # tolerate a torn tail line from a killed run
    return events


def _fmt_s(v: float) -> str:
    return f"{v:10.4f}"


def _fmt_pct(v: float) -> str:
    return f"{100.0 * v:6.1f}%"


def print_goodput_table(events: list[dict], last: int,
                        quiet: bool = False) -> bool:
    windows = [e for e in events if e.get("event") == "goodput"]
    summary = next((e for e in events
                    if e.get("event") == "goodput_summary"), None)
    if not windows and summary is None:
        if not quiet:  # a serving-only file is not a broken train run
            print("no goodput events found (run with cfg.metrics_path "
                  "set)")
        return False
    header = (f"{'window@step':>12} {'steps':>5} {'wall_s':>10} "
              + " ".join(f"{p:>10}" for p in PHASES)
              + f" {'acct':>7}")
    print("== goodput breakdown (seconds; share of wall below) ==")
    print(header)
    for e in windows[-last:]:
        wall = _num(e, "wall_s")
        row = (f"{int(_num(e, 'step', -1)):>12} "
               f"{int(_num(e, 'steps', 1)):>5} "
               + _fmt_s(wall) + " "
               + " ".join(_fmt_s(_num(e, f'{p}_s')) for p in PHASES)
               + f" {_fmt_pct(_num(e, 'accounted_frac')):>7}")
        print(row)
        if wall > 0:
            print(f"{'':>12} {'':>5} {'':>10} "
                  + " ".join(
                      f"{_fmt_pct(_num(e, f'{p}_s') / wall):>10}"
                      for p in PHASES))
    if summary is not None:
        wall = _num(summary, "wall_s")
        print("-- whole run --")
        print(f"{'total':>12} {int(_num(summary, 'steps')):>5} "
              + _fmt_s(wall) + " "
              + " ".join(_fmt_s(_num(summary, f'{p}_s'))
                         for p in PHASES)
              + f" {_fmt_pct(_num(summary, 'accounted_frac')):>7}")
        if wall > 0:
            print(f"{'':>12} {'':>5} {'':>10} "
                  + " ".join(
                      f"{_fmt_pct(_num(summary, f'{p}_s') / wall):>10}"
                      for p in PHASES))
        print(f"goodput (compute+collective share of wall): "
              f"{_fmt_pct(_num(summary, 'goodput_frac')).strip()}")
    return True


def print_comms_table(events: list[dict], trace_dir: str | None) -> None:
    wire = None
    for e in events:
        if e.get("event") in ("goodput", "goodput_summary"):
            wire = e.get("wire_bytes_per_step", wire)
    summary = next((e for e in events
                    if e.get("event") == "goodput_summary"), None)
    if wire is None and trace_dir is None:
        return
    print("\n== comms ==")
    if wire is not None:
        print(f"recorded wire bytes/step (ring accounting): "
              f"{wire / 1e6:.3f} MB")
    ct = None
    if trace_dir:
        from pytorch_distributed_nn_tpu.utils.profiling import (
            collective_trace_seconds,
        )

        import jax

        world = len(jax.devices())
        ct = collective_trace_seconds(trace_dir, world=world)
        if ct is None:
            print(f"no collective slices found under {trace_dir}")
        else:
            print(f"trace-derived collective time: {ct.total_s:.4f}s "
                  f"total / {ct.per_device_s:.4f}s per device "
                  f"({ct.n_events} events)")
            for name, secs in sorted(ct.names.items(),
                                     key=lambda kv: -kv[1])[:8]:
                print(f"    {name:<40} {secs:.4f}s")
    if wire is not None and ct is not None and summary is not None:
        steps = max(summary.get("steps", 1), 1)
        coll_s = ct.per_device_s / steps
        if coll_s > 0:
            print(f"implied bus bandwidth (wire/step ÷ collective "
                  f"s/step): {wire / coll_s / 1e9:.3f} GB/s")


def _num(e: dict, key: str, default: float = 0.0) -> float:
    """Field access that tolerates a torn/partial record from a killed
    run (missing keys, JSON nulls) instead of TypeError-ing mid-table."""
    v = e.get(key, default)
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


def print_metric_tail(events: list[dict], last: int) -> None:
    steps = [e for e in events if e.get("event") == "train_step"]
    evals = [e for e in events if e.get("event") == "eval"]
    if steps:
        print("\n== train tail ==")
        for e in steps[-last:]:
            print(f"step {int(_num(e, 'step', -1)):>6}  "
                  f"loss {_num(e, 'loss'):.4f}  "
                  f"{_num(e, 'samples_per_sec'):>10.1f} samples/s")
    if evals:
        print("== eval tail ==")
        for e in evals[-last:]:
            print(f"step {int(_num(e, 'step', -1)):>6}  "
                  f"loss {_num(e, 'loss'):.4f}  "
                  f"acc {_num(e, 'accuracy'):.4f}")


def _print_tenant_rows(reqs: list[dict], rejects: list[dict]) -> None:
    """Per-tenant breakdown of the serving section (Mosaic). One row
    per tenant: completed requests, tokens out, prefix-cache hit rate
    (``cached_tokens`` over prompt tokens), TTFT p50/p95, and rejects
    split into quota (reason ``tenant_quota``) vs shed (everything
    else). Skipped when the run is single-tenant with no rejects —
    the global percentiles above already tell that story."""
    per: dict[str, list[dict]] = {}
    for e in reqs:
        per.setdefault(str(e.get("tenant", "default")), []).append(e)
    rej: dict[str, list[dict]] = {}
    for e in rejects:
        rej.setdefault(str(e.get("tenant", "default")), []).append(e)
    tenants = sorted(set(per) | set(rej))
    if len(tenants) <= 1 and not rejects:
        return
    print("-- per tenant --")
    print(f"{'tenant':>12} {'reqs':>5} {'tokens':>7} {'hit':>6} "
          f"{'ttft_p50':>10} {'ttft_p95':>10} {'quota':>6} {'shed':>5}")
    for name in tenants:
        rs = per.get(name, [])
        ttft = [_num(e, "ttft_s") for e in rs]
        toks = sum(int(_num(e, "new_tokens")) for e in rs)
        prompt = sum(int(_num(e, "prompt_len")) for e in rs)
        cached = sum(int(_num(e, "cached_tokens")) for e in rs)
        hit = _fmt_pct(cached / prompt).strip() if prompt else "-"
        quota = sum(1 for e in rej.get(name, [])
                    if e.get("reason") == "tenant_quota")
        shed = len(rej.get(name, [])) - quota
        print(f"{name:>12} {len(rs):>5} {toks:>7} {hit:>6} "
              f"{_fmt_s(percentile(ttft, 0.50)) if rs else '         -'} "
              f"{_fmt_s(percentile(ttft, 0.95)) if rs else '         -'} "
              f"{quota:>6} {shed:>5}")


def print_serving_table(events: list[dict], last: int) -> bool:
    """Serving SLO section: per-request TTFT / per-token latency
    percentiles from ``serve_request`` events (scripts/serve.py
    --metrics-out), the per-tenant breakdown (Mosaic: TTFT, prefix-cache
    hit rate from ``cached_tokens``, quota rejects from ``serve_reject``
    events), plus the run-level ``serve_summary`` line. Silently
    skipped when the file has no serving events (training-only runs)."""
    reqs = [e for e in events if e.get("event") == "serve_request"]
    rejects = [e for e in events if e.get("event") == "serve_reject"]
    summary = next((e for e in reversed(events)
                    if e.get("event") == "serve_summary"), None)
    if not reqs and summary is None:
        return False

    print("\n== serving ==")
    if reqs:
        ttft = [_num(e, "ttft_s") for e in reqs]
        ptok = [_num(e, "per_token_s") for e in reqs]
        total = [_num(e, "total_s") for e in reqs]
        toks = sum(int(_num(e, "new_tokens")) for e in reqs)
        print(f"completed requests: {len(reqs)}  tokens out: {toks}")
        print(f"{'':>14} {'p50':>10} {'p95':>10} {'p99':>10}")
        for name, xs in (("ttft_s", ttft), ("per_token_s", ptok),
                         ("total_s", total)):
            print(f"{name:>14} {_fmt_s(percentile(xs, 0.50))} "
                  f"{_fmt_s(percentile(xs, 0.95))} "
                  f"{_fmt_s(percentile(xs, 0.99))}")
        kv = [_num(e, "kv_util") for e in reqs if "kv_util" in e]
        if kv:
            print(f"KV-pool utilization at retire: mean "
                  f"{_fmt_pct(sum(kv) / len(kv)).strip()}, peak "
                  f"{_fmt_pct(max(kv)).strip()}")
        _print_tenant_rows(reqs, rejects)
        print("-- request tail --")
        for e in reqs[-last:]:
            print(f"  {e.get('request_id', '?'):>8}  "
                  f"prompt {int(_num(e, 'prompt_len')):>4}  "
                  f"+{int(_num(e, 'new_tokens')):>3} tok  "
                  f"ttft {_num(e, 'ttft_s') * 1e3:8.2f}ms  "
                  f"tok {_num(e, 'per_token_s') * 1e3:8.3f}ms")
    if summary is not None:
        print("-- run summary --")
        print(f"  requests {int(_num(summary, 'requests'))} "
              f"(completed {int(_num(summary, 'completed'))}, "
              f"rejected {int(_num(summary, 'rejected'))})  "
              f"{_num(summary, 'tokens_per_s'):.1f} tokens/s  "
              f"occupancy {_fmt_pct(_num(summary, 'occupancy')).strip()}  "
              f"kv_util {_fmt_pct(_num(summary, 'kv_util')).strip()}")
        reasons = summary.get("reject_reasons") or {}
        if isinstance(reasons, dict) and reasons:
            why = ", ".join(f"{k}={v}" for k, v in sorted(reasons.items()))
            print(f"  reject reasons: {why}")
    return True


def print_fleet_table(events: list[dict], last: int) -> bool:
    """Replica-fleet section (serve/fleet.py): per-replica occupancy
    from the ``replica`` tag on ``serve_request`` records, replica
    deaths with their stranded requests, failover re-admission latency
    percentiles, and rolling reloads. Silently skipped when the file
    has no fleet events (single-engine and training runs)."""
    downs = [e for e in events if e.get("event") == "fleet_replica_down"]
    fos = [e for e in events if e.get("event") == "fleet_failover"]
    states = [e for e in events if e.get("event") == "fleet_state"]
    reloads = [e for e in events if e.get("event") == "fleet_reload"]
    hoffs = [e for e in events if e.get("event") == "fleet_handoff"]
    xfers = [e for e in events if e.get("event") == "kv_transfer"]
    tagged = [e for e in events
              if e.get("event") == "serve_request" and e.get("replica")]
    if not (downs or fos or states or reloads or hoffs or xfers):
        return False

    print("\n== fleet ==")
    if tagged:
        per: dict[str, list[dict]] = {}
        for e in tagged:
            per.setdefault(str(e.get("replica")), []).append(e)
        print(f"{'replica':>8} {'requests':>9} {'tokens':>8} "
              f"{'ttft_p50':>10} {'ttft_p99':>10}")
        for name in sorted(per):
            rs = per[name]
            ttft = [_num(e, "ttft_s") for e in rs]
            toks = sum(int(_num(e, "new_tokens")) for e in rs)
            print(f"{name:>8} {len(rs):>9} {toks:>8} "
                  f"{_fmt_s(percentile(ttft, 0.50))} "
                  f"{_fmt_s(percentile(ttft, 0.99))}")
    if downs:
        print(f"replica deaths: {len(downs)}")
        for e in downs[-last:]:
            stranded = e.get("stranded") or []
            ids = ", ".join(str(s) for s in stranded) or "(none)"
            print(f"  replica {int(_num(e, 'replica', -1))} DOWN "
                  f"({e.get('reason', '?')}) — stranded: {ids}")
    if fos:
        lat = [_num(e, "readmit_s") for e in fos]
        print(f"failovers: {len(fos)}  re-admission latency "
              f"p50 {percentile(lat, 0.50) * 1e3:.2f}ms  "
              f"p99 {percentile(lat, 0.99) * 1e3:.2f}ms")
        for e in fos[-last:]:
            print(f"  {e.get('request_id', '?'):>8}  "
                  f"r{int(_num(e, 'from_replica', -1))}"
                  f"->r{int(_num(e, 'to_replica', -1))}  "
                  f"prefix {int(_num(e, 'prefix_tokens')):>3} tok  "
                  f"readmit {_num(e, 'readmit_s') * 1e3:8.2f}ms")
    if hoffs:
        # disaggregated fleet (serve/disagg.py): prefill->decode
        # handoffs and the KV block streams that warm them
        pfx = [_num(e, "prefix_tokens") for e in hoffs]
        print(f"prefill->decode handoffs: {len(hoffs)}  "
              f"stitched prefix p50 {percentile(pfx, 0.50):.0f} tok  "
              f"p99 {percentile(pfx, 0.99):.0f} tok")
    if xfers:
        n_ok = sum(1 for e in xfers if e.get("outcome") == "ok")
        failed = [e for e in xfers if e.get("outcome") == "failed"]
        total_b = sum(_num(e, "bytes") for e in xfers)
        print(f"kv transfers: {len(xfers)} ({n_ok} ok, "
              f"{len(failed)} failed)  "
              f"{total_b / 1e6:.2f} MB streamed")
        for e in failed[-last:]:
            print(f"  r{int(_num(e, 'src', -1))}"
                  f"->r{int(_num(e, 'dst', -1))} FAILED mid-transfer "
                  f"({int(_num(e, 'blocks'))} blocks)")
    if reloads:
        rolled = sum(int(_num(e, "replicas")) for e in reloads)
        print(f"rolling reloads: {len(reloads)} "
              f"({rolled} replica(s) rolled)")
    return True


def print_trace_table(events: list[dict], last: int) -> bool:
    """Causeway section (obs/trace.py): per-segment latency
    percentiles across every traced request in the stream, plus the
    dominant-segment table — for each trace, which segment owned the
    most critical-path time (obs/critpath.py attribution). Silently
    skipped when the file has no ``trace_span`` events (TPUNN_TRACE
    unset). Full waterfalls: ``scripts/obs_trace.py`` on this file."""
    spans = [{k: v for k, v in e.items()
              if k not in ("event", "time", "process")}
             for e in events if e.get("event") == "trace_span"]
    if not spans:
        return False
    from pytorch_distributed_nn_tpu.obs import critpath

    print("\n== request traces (Causeway) ==")
    durs = [s for s in spans
            if s.get("segment") in critpath.PRIORITY
            and _num(s, "t1") > _num(s, "t0")]
    per_seg: dict[str, list[float]] = {}
    for s in durs:
        per_seg.setdefault(str(s["segment"]), []).append(
            _num(s, "t1") - _num(s, "t0"))
    traces = sorted({str(s.get("trace", "")) for s in spans})
    print(f"{len(traces)} trace(s), {len(spans)} span(s)")
    if per_seg:
        print(f"{'segment':>9} {'spans':>6} {'p50':>10} {'p99':>10}")
        for seg in sorted(per_seg,
                          key=lambda k: -critpath.PRIORITY[k]):
            xs = per_seg[seg]
            print(f"{seg:>9} {len(xs):>6} "
                  f"{_fmt_s(percentile(xs, 0.50))} "
                  f"{_fmt_s(percentile(xs, 0.99))}")
    dominated: dict[str, int] = {}
    worst: list[tuple[float, str, str]] = []
    for t in traces:
        cp = critpath.critical_path(
            [s for s in spans if str(s.get("trace", "")) == t])
        if not cp["segments"]:
            continue
        dominated[cp["dominant"]] = dominated.get(cp["dominant"], 0) + 1
        worst.append((cp["total_s"], t, cp["dominant"]))
    if dominated:
        print("dominant segment: " + ", ".join(
            f"{seg} x{n}" for seg, n in
            sorted(dominated.items(), key=lambda kv: -kv[1])))
    for total, t, dom in sorted(worst, reverse=True)[:last]:
        print(f"  {t}  {total * 1e3:8.1f}ms  dominated by {dom}")
    return True


def print_cost_table(events: list[dict], last: int) -> bool:
    """Abacus section (obs/meter.py): the per-tenant resource bill —
    FLOPs, KV block-seconds, wire bytes, queue/decode wall time —
    from the ``meter_ledger`` records the meter flushes at every
    summary boundary (cumulative, so last-per-tenant wins), plus the
    costliest individual requests from the ``meter_request`` tail.
    Silently skipped when the file has no ledger records (TPUNN_METER
    unset). Pricing + the full showback: ``scripts/obs_cost.py``."""
    from pytorch_distributed_nn_tpu.obs.meter import (
        LEDGER_FIELDS, UNATTRIBUTED, ledger_totals)
    ledgers: dict[str, dict[str, int]] = {}
    for e in events:
        if e.get("event") != "meter_ledger":
            continue
        ledgers[str(e.get("tenant", UNATTRIBUTED))] = {
            k: int(e.get(k, 0)) for k in LEDGER_FIELDS}
    if not ledgers:
        return False
    print("\n== tenant billing (Abacus) ==")
    print(f"{'tenant':>12} {'reqs':>5} {'tokens':>7} {'GFLOPs':>10} "
          f"{'kv_blk_s':>9} {'wire_MB':>8} {'decode_s':>9}")
    rows = sorted(ledgers.items(),
                  key=lambda kv: -kv[1]["flops"])
    totals = ledger_totals(ledgers)
    for tenant, led in rows + [("TOTAL", totals)]:
        print(f"{tenant:>12} {led['requests']:>5} {led['tokens']:>7} "
              f"{led['flops'] / 1e9:>10.3f} "
              f"{led['kv_block_us'] / 1e6:>9.3f} "
              f"{led['wire_bytes'] / 1e6:>8.3f} "
              f"{led['decode_us'] / 1e6:>9.3f}")
    if totals["saved_tokens"]:
        print(f"prefix-cache savings: {totals['saved_tokens']} "
              f"token(s) / {totals['saved_flops'] / 1e9:.3f} GFLOPs "
              f"not recomputed")
    reqs = [e for e in events if e.get("event") == "meter_request"]
    for e in sorted(reqs, key=lambda e: -_num(e, "flops"))[:last]:
        print(f"  {e.get('tenant', UNATTRIBUTED):>12} "
              f"{str(e.get('request_id', '')):>8} "
              f"{_num(e, 'flops') / 1e9:10.3f} GFLOPs "
              f"{int(_num(e, 'tokens'))} token(s)")
    return True


def print_audit_table(events: list[dict], last: int) -> bool:
    """Lighthouse section (obs/audit.py): output-integrity coverage —
    how many ``serve_request`` records carry a fingerprint chain,
    every confirmed divergence with its replica pair and suspect,
    golden-probe pass/fail tallies, and quarantined replicas with the
    work re-admitted off them. Silently skipped when the file has no
    audit events (TPUNN_AUDIT unset). The standalone report + the
    tier-1 corruption drill: ``scripts/obs_audit.py``."""
    reqs = [e for e in events if e.get("event") == "serve_request"]
    fps = [e for e in reqs if e.get("fp")]
    divs = [e for e in events if e.get("event") == "audit_divergence"]
    probes = [e for e in events if e.get("event") == "audit_probe"]
    quars = [e for e in events if e.get("event") == "fleet_quarantine"]
    if not (fps or divs or probes or quars):
        return False
    print("\n== output integrity (Lighthouse) ==")
    if reqs:
        print(f"fingerprints: {len(fps)} of {len(reqs)} "
              f"request record(s) carry a token chain")
    if probes:
        failed = sum(1 for e in probes if not int(_num(e, "ok", 1)))
        print(f"golden probes: {len(probes)} ({failed} failed)")
    if divs:
        print(f"divergences: {len(divs)} confirmed")
        for e in divs[-last:]:
            pair = ",".join(str(p) for p in e.get("pair") or [])
            print(f"  {e.get('kind', '?'):>8} "
                  f"{str(e.get('request_id', '')):>10} "
                  f"pair={pair or '-'} suspect={e.get('suspect', '?')}")
    for e in quars[-last:]:
        stranded = e.get("stranded") or []
        ids = ", ".join(str(s) for s in stranded) or "(none)"
        print(f"quarantined: replica {int(_num(e, 'replica', -1))} "
              f"({e.get('reason', '?')}) — re-admitted: {ids}")
    return True


def print_capacity_table(events: list[dict], last: int,
                         requested: bool = False) -> bool:
    """Skyline capacity-planning section (obs/capacity.py): the
    offered-load rung table per replica count with each SLO class's
    verdict, the sustainable frontier + goodput knee, the "replicas
    needed per SLO" plan line, and — under a chaos drill — the failover
    windows that moved the frontier. Silently skipped when the file has
    no ``capacity_*`` events unless ``--capacity`` asked for it."""
    rungs = [e for e in events if e.get("event") == "capacity_rung"]
    fronts = [e for e in events
              if e.get("event") == "capacity_frontier"]
    plan = next((e for e in reversed(events)
                 if e.get("event") == "capacity_plan"), None)
    if not (rungs or fronts or plan):
        if requested:
            print("\nno capacity events found (write them with "
                  "bench.py --capacity --capacity-out FILE)")
        return False

    print("\n== capacity frontier (Skyline) ==")
    if plan is not None:
        line = (f"shape {plan.get('shape', '?')}  "
                f"seed {int(_num(plan, 'seed'))}")
        if plan.get("chaos"):
            line += f"  chaos {plan['chaos']}"
        print(line)
        print(f"  spec: {plan.get('spec', '?')}")
    slo_names = sorted({name for e in rungs
                        for name in (e.get("slo") or {})})
    if rungs:
        print(f"{'replicas':>8} {'offered':>9} {'goodput':>9} "
              f"{'rej':>5} "
              + " ".join(f"{n:>16}" for n in slo_names))
        for e in rungs:  # a sweep is small; truncation hides the knee
            cells = []
            for name in slo_names:
                j = (e.get("slo") or {}).get(name) or {}
                tag = "ok" if j.get("sustainable") else "BURN"
                cells.append(f"{tag:>4} "
                             f"{_fmt_pct(_num(j, 'attainment')).strip():>6}"
                             f" p{int(_num(j, 'burn_pages')):<3}")
            print(f"{int(_num(e, 'replicas')):>8} "
                  f"{_num(e, 'offered_rps'):>9.2f} "
                  f"{_num(e, 'goodput_tps'):>9.1f} "
                  f"{int(_num(e, 'rejects')):>5} "
                  + " ".join(cells))
    for e in fronts:
        front = e.get("frontier") or {}
        parts = [f"{k} {v:.2f} req/s" if v is not None
                 else f"{k} none" for k, v in sorted(front.items())]
        knee = e.get("knee_rps")
        print(f"frontier @{int(_num(e, 'replicas'))} replica(s): "
              + ", ".join(parts)
              + (f"  (goodput knee {knee:.2f} rps)"
                 if knee is not None else "  (no saturation knee)"))
    wins = [(int(_num(e, "replicas")), w) for e in rungs
            for w in (e.get("failover_windows") or [])]
    if wins:
        print(f"failover windows (chaos drill): {len(wins)}")
        for n, w in wins[-last:]:
            rec = w.get("t_recovered")
            print(f"  @{n} replica(s): replica "
                  f"{int(_num(w, 'replica', -1))} down "
                  f"t={_num(w, 't_down'):.2f}s, "
                  f"{int(_num(w, 'readmitted'))} re-admitted, "
                  + (f"recovered t={rec:.2f}s" if rec is not None
                     else "no re-admissions to recover"))
    if plan is not None:
        needed = plan.get("replicas_needed") or {}
        for name in sorted(needed):
            d = needed[name] or {}
            n = d.get("replicas")
            print(f"replicas needed [{name}] for "
                  f"{_num(d, 'target_rps'):.2f} req/s: "
                  + (str(int(n)) if n is not None
                     else "none swept suffices"))
    return True


def print_autoscale_table(events: list[dict], last: int,
                          requested: bool = False) -> bool:
    """Helm section (serve/autoscale.py): the replica trajectory as
    the autoscaler steered it — every scale_up/scale_down with the
    journaled evidence that drove it (per-window burns, queue/KV
    fractions, forecast floor), plus the hold-reason tally. Silently
    skipped when the file has no ``autoscale_decision`` events unless
    ``--autoscale`` asked for it."""
    decs = [e for e in events
            if e.get("event") == "autoscale_decision"]
    if not decs:
        if requested:
            print("\nno autoscale decisions found (write them with "
                  "bench.py --autoscale --autoscale-out FILE)")
        return False

    print("\n== autoscale decisions (Helm) ==")
    lastd = decs[-1]
    ev = lastd.get("evidence") or {}
    print(f"policy: {lastd.get('spec', '?')}")
    fc = ev.get("forecast_replicas")
    print(f"decisions {len(decs)}, final target "
          f"{int(_num(lastd, 'to_replicas'))}"
          + (f", Skyline forecast {int(fc)}" if fc is not None
             else ", no Skyline forecast"))
    holds: dict[str, int] = {}
    actions = []
    for d in decs:
        if d.get("action") == "hold":
            r = str(d.get("reason", "?"))
            holds[r] = holds.get(r, 0) + 1
        else:
            actions.append(d)
    if holds:
        print("holds: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(holds.items())))
    if actions:
        print(f"{'t':>10} {'action':>10} {'replicas':>9} "
              f"{'burn f/s':>11} {'queue':>6} {'kv':>5}  reason")
        for d in actions:  # a trajectory is small; holds are tallied
            e = d.get("evidence") or {}
            burns = (e.get("burn") or {}).get("ttft") or {}
            print(f"{_num(d, 't'):>10.2f} {d.get('action', '?'):>10} "
                  f"{int(_num(d, 'from_replicas')):>4}->"
                  f"{int(_num(d, 'to_replicas')):<4} "
                  f"{_num(burns, 'fast'):>5.2f}/"
                  f"{_num(burns, 'slow'):<5.2f} "
                  f"{_fmt_pct(_num(e, 'queue_frac')).strip():>6} "
                  f"{_fmt_pct(_num(e, 'kv_free_frac')).strip():>5}"
                  f"  {d.get('reason', '?')}")
    else:
        print("no scale actions (steady)")
    return True


def print_xray_table(xray_dir: str | None, last: int) -> bool:
    """Xray section: per-op attribution from anomaly-triggered
    ``obs.xray`` captures under ``--xray DIR``. Silently skipped when
    no directory is given; noisy when one is given but holds no
    captures (the operator asked and should hear "nothing there")."""
    if not xray_dir:
        return False
    from pytorch_distributed_nn_tpu.obs import xray

    paths = xray.find_captures(xray_dir)
    if not paths:
        print(f"\nno xray captures under {xray_dir}")
        return False
    print("\n== xray captures ==")
    for p in paths[-last:]:
        try:
            summary = xray.load_capture(p)
        except (OSError, json.JSONDecodeError):
            print(f"  unreadable capture: {p}")
            continue
        att = summary.get("attribution") or {}
        print(f"-- {summary.get('reason', '?')} at step "
              f"{summary.get('trigger_step', -1)} "
              f"(source={att.get('source', 'none')}) --")
        table = xray.render_op_table(att, top=last)
        if table:
            print(table)
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("jsonl", help="metrics JSONL path "
                                  "(TrainConfig.metrics_path)")
    ap.add_argument("--trace", default="",
                    help="xprof trace dir (perfetto_trace.json.gz) for "
                         "the trace-derived collective cross-check")
    ap.add_argument("--xray", default="",
                    help="directory holding obs.xray capture dirs "
                         "(xray_*/xray_summary.json) to render")
    ap.add_argument("--capacity", action="store_true",
                    help="insist on the Skyline capacity section "
                         "(noisy when the file has no capacity_* "
                         "events; auto-rendered when it does)")
    ap.add_argument("--autoscale", action="store_true",
                    help="insist on the Helm autoscale section "
                         "(noisy when the file has no "
                         "autoscale_decision events; auto-rendered "
                         "when it does)")
    ap.add_argument("--last", type=int, default=5,
                    help="windows/rows to show per table")
    args = ap.parse_args(argv)
    try:
        events = load_events(args.jsonl)
    except OSError as e:
        print(f"cannot read {args.jsonl}: {e}", file=sys.stderr)
        return 1
    if not events:
        # an empty or torn stream is a quiet report, not a crash —
        # monitoring wrappers run this before the workload has
        # emitted anything
        print(f"no events in {args.jsonl}")
        if args.xray:
            print_xray_table(args.xray, args.last)
        return 0
    has_serve = any(e.get("event") in
                    ("serve_request", "serve_summary", "fleet_state",
                     "fleet_replica_down", "fleet_failover",
                     "fleet_reload", "fleet_handoff", "kv_transfer",
                     "trace_span", "meter_ledger", "capacity_rung",
                     "capacity_frontier", "capacity_plan",
                     "autoscale_decision", "audit_divergence",
                     "audit_probe", "fleet_quarantine")
                    for e in events)
    ok = print_goodput_table(events, args.last, quiet=has_serve)
    print_comms_table(events, args.trace or None)
    serve_ok = print_serving_table(events, args.last)
    fleet_ok = print_fleet_table(events, args.last)
    trace_ok = print_trace_table(events, args.last)
    cost_ok = print_cost_table(events, args.last)
    audit_ok = print_audit_table(events, args.last)
    cap_ok = print_capacity_table(events, args.last,
                                  requested=args.capacity)
    helm_ok = print_autoscale_table(events, args.last,
                                    requested=args.autoscale)
    xray_ok = print_xray_table(args.xray or None, args.last)
    print_metric_tail(events, args.last)
    if not (ok or serve_ok or fleet_ok or trace_ok or cost_ok
            or audit_ok or cap_ok or helm_ok or xray_ok):
        print("nothing to report (no recognized event families)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
