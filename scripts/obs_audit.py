#!/usr/bin/env python
"""Lighthouse integrity report: fingerprints, divergences, quarantines
(obs/audit.py).

Reads the JSONL metrics stream an audited serving run wrote
(``TPUNN_AUDIT=`` armed + a ``metrics=`` sink) and prints the output-
integrity picture: how many requests carry a token-fingerprint chain,
every confirmed divergence (shadow-replay mismatch, golden-probe
failure, worker chain break) with the replica pair and the suspect the
majority named, golden-probe pass/fail tallies, and which replicas were
quarantined — with the reason and how many in-flight requests were
re-admitted on survivors.

A stream with no audit activity renders a one-line quiet report and
exits 0 — absence of evidence is the healthy steady state, not an
error. Torn tail lines (a killed run) are tolerated.

Usage:
    python scripts/obs_audit.py runs/metrics.jsonl          # table
    python scripts/obs_audit.py runs/metrics.jsonl --json   # canonical
    python scripts/obs_audit.py --selftest                  # tier-1 gate

The ``--selftest`` drill (the tier-1 acceptance gate, run as a
subprocess smoke by tests/test_quality.py) is the end-to-end silent-
corruption story: an UNARMED baseline run over a 3-replica fleet
records the honest outputs (and proves the audit writes nothing — no
registry counters, no flight-ring events, no ``fp`` keys); then the
same workload runs with ``TPUNN_AUDIT=sample=1.0:quarantine=1`` armed
and ``flip@replica=1:step=3`` chaos corrupting one decoded token on
replica 1. The drill asserts the full chain reacted: a watchtower
``output_divergence`` page names r1 as the suspect, r1 lands in
QUARANTINED (through the counted ``_set_state`` choke point — router
excludes it, no restart is ever scheduled), the requests stranded on
r1 re-admit on the survivors (``failovers > 0``), and every final
client-visible token stream is BIT-IDENTICAL to the unarmed baseline
— the corruption never reached a caller.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, ".")  # run from repo root without install

from pytorch_distributed_nn_tpu.runtime.platform import (  # noqa: E402
    apply_platform_overrides,
)

apply_platform_overrides()


def load_events(path: str) -> list[dict]:
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # tolerate a torn tail line from a killed run
    return events


def build_report(events: list[dict]) -> dict:
    """The canonical integrity report dict. Pure in its inputs — same
    events, same bytes (``to_json``)."""
    total = fingerprinted = 0
    divergences: list[dict] = []
    by_kind: dict[str, int] = {}
    probes = probe_failures = 0
    quarantines: list[dict] = []
    for e in events:
        ev = e.get("event")
        if ev == "serve_request":
            total += 1
            if e.get("fp"):
                fingerprinted += 1
        elif ev == "audit_divergence":
            rec = {"kind": str(e.get("kind", "")),
                   "request_id": str(e.get("request_id", "")),
                   "pair": [str(p) for p in e.get("pair", [])],
                   "suspect": str(e.get("suspect", ""))}
            divergences.append(rec)
            by_kind[rec["kind"]] = by_kind.get(rec["kind"], 0) + 1
        elif ev == "audit_probe":
            probes += 1
            if not int(e.get("ok", 1)):
                probe_failures += 1
        elif ev == "fleet_quarantine":
            stranded = e.get("stranded", [])
            quarantines.append({
                "replica": int(e.get("replica", -1)),
                "reason": str(e.get("reason", "")),
                "stranded": (len(stranded)
                             if isinstance(stranded, list)
                             else int(stranded))})
    return {
        "requests": {"total": total, "fingerprinted": fingerprinted},
        "divergences": divergences,
        "divergences_by_kind": {k: by_kind[k] for k in sorted(by_kind)},
        "probes": {"total": probes, "failed": probe_failures},
        "quarantines": quarantines,
    }


def is_quiet(report: dict) -> bool:
    """No audit activity at all — the healthy (or unarmed) stream."""
    return (report["requests"]["fingerprinted"] == 0
            and not report["divergences"]
            and report["probes"]["total"] == 0
            and not report["quarantines"])


def to_json(report: dict) -> str:
    """Canonical bytes — the determinism unit the selftest asserts."""
    return json.dumps(report, sort_keys=True)


def render(report: dict) -> str:
    lines: list[str] = []
    out = lines.append
    out("== Lighthouse output integrity (obs/audit.py) ==")
    r = report["requests"]
    out(f"fingerprints: {r['fingerprinted']} of {r['total']} "
        f"request record(s) carry a token chain")
    p = report["probes"]
    if p["total"]:
        out(f"golden probes: {p['total']} run, {p['failed']} failed")
    if report["divergences"]:
        out(f"divergences: {len(report['divergences'])} confirmed "
            + " ".join(f"{k}={n}" for k, n in
                       report["divergences_by_kind"].items()))
        for d in report["divergences"]:
            out(f"  {d['kind']:>8} {d['request_id'] or '(probe)':>20} "
                f"pair={','.join(d['pair'])} suspect={d['suspect']}")
    else:
        out("divergences: none")
    if report["quarantines"]:
        for q in report["quarantines"]:
            out(f"quarantined: replica {q['replica']} "
                f"({q['reason']}) — {q['stranded']} in-flight "
                f"request(s) re-admitted on survivors")
    else:
        out("quarantines: none")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# --selftest: the end-to-end silent-corruption drill (tier-1 gate)
# ---------------------------------------------------------------------------

def _run_workload(model, params, jobs, metrics=None):
    """One fleet pass over the canned workload (``jobs`` is a list of
    ``(request_id, prompt, budget)``); returns (per-ticket token
    lists, fleet). Greedy + seed-pinned: bit-reproducible."""
    from pytorch_distributed_nn_tpu.serve import Fleet

    fleet = Fleet(model, params, replicas=3, max_slots=2,
                  max_seq_len=96, block_size=16, metrics=metrics)
    tickets = [fleet.submit(p, b, request_id=rid)
               for rid, p, b in jobs]
    fleet.run_until_idle()
    outs = []
    for t in tickets:
        assert t.ok, (t.request_id, t.status, t.reject_reason)
        outs.append([int(x) for x in t.tokens])
    return outs, fleet


def _selftest() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    apply_platform_overrides()
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_nn_tpu import obs
    from pytorch_distributed_nn_tpu.config import ModelConfig
    from pytorch_distributed_nn_tpu.obs import audit, flight, watchtower
    from pytorch_distributed_nn_tpu.runtime import chaos
    from pytorch_distributed_nn_tpu.serve.router import QUARANTINED
    from pytorch_distributed_nn_tpu.utils.metrics import MetricsLogger

    from pytorch_distributed_nn_tpu.models import get_model

    vocab = 97
    model = get_model(ModelConfig(
        name="llama3_8b", compute_dtype="float32", dtype="float32",
        extra=dict(num_layers=2, d_model=64, num_heads=4,
                   num_kv_heads=2, mlp_dim=128, vocab_size=vocab)))
    params = model.init(jax.random.key(1),
                        jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    rng = np.random.default_rng(7)
    # "lh-5" is the only request whose id hashes under sample=0.25,
    # so it alone grows a shadow leg (which the router places on r1,
    # where the chaos flip corrupts it).  It is short: its shadow
    # comparison settles while the unsampled long requests still
    # decode — which is what strands a real, journaled leg on r1 at
    # quarantine time and forces a failover re-admission.  Exactly
    # three long requests: one lands on each replica, which keeps a
    # slot free on r2 for the referee leg (a full r2 would queue the
    # referee behind 24-token decodes and settle the divergence only
    # after every real leg had already finished).
    short = rng.integers(1, vocab, size=(10,)).astype(np.int32)
    longs = [rng.integers(1, vocab, size=(n,)).astype(np.int32)
             for n in (12, 9, 14)]
    jobs = [("lh-5", short, 4)] + [
        (f"lh-{i}", p, 24) for i, p in enumerate(longs)]

    # -- unarmed baseline: the honest outputs, and proof of inertness --
    audit.reset()
    chaos.reset()
    watchtower.reset()
    obs.reset_registry()
    flight.reset_recorder(enabled=True)
    baseline, fleet0 = _run_workload(model, params, jobs)
    assert audit.summary() is None, "unarmed audit has state"
    assert audit.seed_of([1, 2]) == "", "unarmed seed_of not inert"
    assert not audit.shadow_sampled("lh-5"), "unarmed sample not inert"
    ring = [ev for ev in flight.get_recorder().snapshot()
            if ev["kind"] == "audit"]
    assert not ring, f"unarmed run wrote audit ring events: {ring}"
    assert all("fp" not in r for r in fleet0.completed), \
        "unarmed serve_request records carry fp keys"

    # -- armed run + chaos flip: the whole chain must react ------------
    audit.reset()
    chaos.reset()
    watchtower.reset()
    obs.reset_registry()
    flight.reset_recorder(enabled=True)
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "metrics.jsonl")
        with MetricsLogger(path) as m:
            assert audit.maybe_init("sample=0.25:quarantine=1",
                                    rank=0, metrics=m) is not None
            assert audit.shadow_sampled("lh-5"), "lh-5 not in sample"
            assert not audit.shadow_sampled("lh-0"), "lh-0 in sample"
            chaos.maybe_init("flip@replica=1:step=3", rank=0, seed=0)
            watchtower.maybe_init("1", rank=0, metrics=m)
            armed, fleet = _run_workload(model, params, jobs,
                                         metrics=m)

            # 1. the corruption never reached a caller: every stream
            # bit-identical to the unarmed baseline
            assert armed == baseline, "outputs diverged from baseline"

            # 2. the page names replica 1 as the suspect
            tw = watchtower.tower()
            pages = [a for a in tw.alerts
                     if a.kind == "output_divergence"]
            assert pages, "no output_divergence page raised"
            assert any("r1" in a.detail for a in pages), \
                [a.detail for a in pages]

            # 3. r1 is QUARANTINED through the counted choke point —
            # excluded, not restarted
            h1 = next(h for h in fleet.replicas if h.index == 1)
            assert h1.state == QUARANTINED, h1.state
            assert h1.restart_at is None, "quarantine scheduled restart"
            assert h1.stop_reason.startswith("quarantined:"), \
                h1.stop_reason
            live = [h.index for h in fleet.replicas
                    if h.state == "ready"]
            assert live == [0, 2], live

            # 4. in-flight requests re-admitted on survivors
            assert fleet.failovers > 0, \
                "quarantine stranded no in-flight work"
            moved = [t for i, t in enumerate(fleet.completed)
                     if t.get("failovers")]
            assert moved, "no completed request records a failover"

            # 5. the audit engine's own books agree
            s = fleet.summary()["audit"]
            assert s["divergences"] >= 1, s
            assert any(q["replica"] == "r1"
                       for q in s["quarantines"]), s

        # 6. the JSONL stream renders the same story, deterministically
        events = load_events(path)
        report = build_report(events)
        assert report["requests"]["fingerprinted"] > 0, report
        assert report["divergences"], report
        assert any(d["suspect"] == "r1"
                   for d in report["divergences"]), report
        assert any(q["replica"] == 1 for q in report["quarantines"]), \
            report
        assert not is_quiet(report)
        assert to_json(report) == to_json(
            build_report(load_events(path))), "report not deterministic"
        print(render(report))

        # 7. an empty stream is a quiet rc-0 report, not a crash
        empty = os.path.join(td, "empty.jsonl")
        open(empty, "w").close()
        assert is_quiet(build_report(load_events(empty)))

    audit.reset()
    chaos.reset()
    watchtower.reset()
    print("obs_audit selftest ok: flip on r1 paged, quarantined, "
          f"{fleet.failovers} failover(s), outputs bit-identical "
          f"({len(baseline)} streams)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("jsonl", nargs="?", default="",
                    help="metrics JSONL an audited run wrote")
    ap.add_argument("--json", action="store_true",
                    help="print the canonical report JSON instead of "
                         "the table")
    ap.add_argument("--selftest", action="store_true",
                    help="run the flip->page->quarantine->re-admit "
                         "drill (tier-1 acceptance gate)")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.jsonl:
        ap.error("need a metrics JSONL path (or --selftest)")
    if not os.path.exists(args.jsonl):
        print(f"no such file: {args.jsonl}")
        return 1
    report = build_report(load_events(args.jsonl))
    if is_quiet(report):
        print(f"no audit activity in {args.jsonl} "
              f"(run with TPUNN_AUDIT= armed and a metrics sink)")
        return 0
    print(to_json(report) if args.json else render(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
