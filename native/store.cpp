// TCP key-value rendezvous store — the TPU framework's equivalent of
// c10d's TCPStore (SURVEY.md §2b "torchrun elastic agent / c10d TCPStore"
// row): multi-host rendezvous, atomic counters for rank assignment,
// blocking key waits for barriers, heartbeat keys for failure detection.
//
// The reference freeloads on torch's C++ TCPStore; this is a fresh
// implementation with the same capability surface, C ABI (driven from
// Python via ctypes — no pybind11 in this image).
//
// Protocol (client -> server), length-prefixed binary over one TCP
// connection per client:
//   u8 op | u32 klen | key | u32 vlen | value
// ops: 1=SET 2=GET(blocking, vlen=timeout_ms) 3=ADD(vlen=8, i64 delta)
//      4=CHECK 5=DELETE
// reply: u8 status (0=ok, 1=timeout/missing) | u32 vlen | value

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> data;
};

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::vector<std::thread> clients;
  std::vector<int> client_fds;  // parallel to clients; for shutdown()
  std::mutex clients_mu;
  Store store;
};

bool read_exact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_reply(int fd, uint8_t status, const std::string& val) {
  uint32_t vlen = static_cast<uint32_t>(val.size());
  if (!write_exact(fd, &status, 1)) return false;
  if (!write_exact(fd, &vlen, 4)) return false;
  if (vlen && !write_exact(fd, val.data(), vlen)) return false;
  return true;
}

void serve_client(Server* srv, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  for (;;) {
    uint8_t op;
    uint32_t klen, vlen;
    if (!read_exact(fd, &op, 1)) break;
    if (!read_exact(fd, &klen, 4)) break;
    std::string key(klen, '\0');
    if (klen && !read_exact(fd, key.data(), klen)) break;
    if (!read_exact(fd, &vlen, 4)) break;
    std::string val(vlen, '\0');
    if (vlen && !read_exact(fd, val.data(), vlen)) break;

    Store& st = srv->store;
    bool ok = true;
    switch (op) {
      case 1: {  // SET
        {
          std::lock_guard<std::mutex> lk(st.mu);
          st.data[key] = val;
        }
        st.cv.notify_all();
        ok = send_reply(fd, 0, "");
        break;
      }
      case 2: {  // GET with blocking wait; value carries i64 timeout_ms
        int64_t timeout_ms = -1;
        if (val.size() == 8) std::memcpy(&timeout_ms, val.data(), 8);
        std::unique_lock<std::mutex> lk(st.mu);
        auto ready = [&] { return st.data.count(key) > 0; };
        bool found;
        if (timeout_ms < 0) {
          st.cv.wait(lk, [&] { return ready() || srv->stop.load(); });
          found = ready();
        } else {
          found = st.cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                 [&] { return ready() || srv->stop.load(); });
          found = found && ready();
        }
        std::string out = found ? st.data[key] : "";
        lk.unlock();
        ok = send_reply(fd, found ? 0 : 1, out);
        break;
      }
      case 3: {  // ADD: i64 delta; creates at 0; returns new value
        int64_t delta = 0;
        if (val.size() == 8) std::memcpy(&delta, val.data(), 8);
        int64_t now;
        {
          std::lock_guard<std::mutex> lk(st.mu);
          int64_t cur = 0;
          auto it = st.data.find(key);
          if (it != st.data.end() && it->second.size() == 8)
            std::memcpy(&cur, it->second.data(), 8);
          now = cur + delta;
          std::string enc(8, '\0');
          std::memcpy(enc.data(), &now, 8);
          st.data[key] = enc;
        }
        st.cv.notify_all();
        std::string out(8, '\0');
        std::memcpy(out.data(), &now, 8);
        ok = send_reply(fd, 0, out);
        break;
      }
      case 4: {  // CHECK (non-blocking exists)
        bool found;
        {
          std::lock_guard<std::mutex> lk(st.mu);
          found = st.data.count(key) > 0;
        }
        ok = send_reply(fd, found ? 0 : 1, "");
        break;
      }
      case 5: {  // DELETE
        {
          std::lock_guard<std::mutex> lk(st.mu);
          st.data.erase(key);
        }
        st.cv.notify_all();
        ok = send_reply(fd, 0, "");
        break;
      }
      default:
        ok = false;
    }
    if (!ok) break;
  }
  ::close(fd);
}

void accept_loop(Server* srv) {
  for (;;) {
    int fd = ::accept(srv->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (srv->stop.load()) return;
      continue;
    }
    std::lock_guard<std::mutex> lk(srv->clients_mu);
    srv->client_fds.push_back(fd);
    srv->clients.emplace_back(serve_client, srv, fd);
  }
}

struct Client {
  int fd = -1;
  std::mutex mu;  // one in-flight request per client handle
};

bool client_request(Client* c, uint8_t op, const std::string& key,
                    const std::string& val, uint8_t* status,
                    std::string* out) {
  std::lock_guard<std::mutex> lk(c->mu);
  uint32_t klen = static_cast<uint32_t>(key.size());
  uint32_t vlen = static_cast<uint32_t>(val.size());
  if (!write_exact(c->fd, &op, 1)) return false;
  if (!write_exact(c->fd, &klen, 4)) return false;
  if (klen && !write_exact(c->fd, key.data(), klen)) return false;
  if (!write_exact(c->fd, &vlen, 4)) return false;
  if (vlen && !write_exact(c->fd, val.data(), vlen)) return false;
  if (!read_exact(c->fd, status, 1)) return false;
  uint32_t rlen;
  if (!read_exact(c->fd, &rlen, 4)) return false;
  out->assign(rlen, '\0');
  if (rlen && !read_exact(c->fd, out->data(), rlen)) return false;
  return true;
}

}  // namespace

extern "C" {

// ---- server ----------------------------------------------------------

void* tpustore_server_start(int port) {
  auto* srv = new Server();
  srv->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (srv->listen_fd < 0) {
    delete srv;
    return nullptr;
  }
  int one = 1;
  setsockopt(srv->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(srv->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0 ||
      ::listen(srv->listen_fd, 128) != 0) {
    ::close(srv->listen_fd);
    delete srv;
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(srv->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
  srv->port = ntohs(addr.sin_port);
  srv->accept_thread = std::thread(accept_loop, srv);
  return srv;
}

int tpustore_server_port(void* handle) {
  return handle ? static_cast<Server*>(handle)->port : -1;
}

void tpustore_server_stop(void* handle) {
  if (!handle) return;
  auto* srv = static_cast<Server*>(handle);
  srv->stop.store(true);
  srv->store.cv.notify_all();
  ::shutdown(srv->listen_fd, SHUT_RDWR);
  ::close(srv->listen_fd);
  if (srv->accept_thread.joinable()) srv->accept_thread.join();
  {
    // Wake every handler blocked in read()/cv-wait, then JOIN them —
    // they dereference srv->store, so srv must outlive them.
    std::lock_guard<std::mutex> lk(srv->clients_mu);
    for (int fd : srv->client_fds) ::shutdown(fd, SHUT_RDWR);
    for (auto& t : srv->clients)
      if (t.joinable()) t.join();
  }
  delete srv;
}

// ---- client ----------------------------------------------------------

void* tpustore_connect(const char* host, int port, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  for (;;) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      ::close(fd);
      return nullptr;
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) ==
        0) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto* c = new Client();
      c->fd = fd;
      return c;
    }
    ::close(fd);
    if (std::chrono::steady_clock::now() >= deadline) return nullptr;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

void tpustore_disconnect(void* handle) {
  if (!handle) return;
  auto* c = static_cast<Client*>(handle);
  ::close(c->fd);
  delete c;
}

int tpustore_set(void* handle, const char* key, const uint8_t* val,
                 int vlen) {
  uint8_t status;
  std::string out;
  std::string v(reinterpret_cast<const char*>(val),
                static_cast<size_t>(vlen));
  if (!client_request(static_cast<Client*>(handle), 1, key, v, &status,
                      &out))
    return -1;
  return status == 0 ? 0 : -2;
}

// Blocking get. Returns value length (>=0), -1 on I/O error, -2 on
// timeout. If the value is larger than cap, returns -3 (caller grows).
int tpustore_get(void* handle, const char* key, uint8_t* buf, int cap,
                 int64_t timeout_ms) {
  uint8_t status;
  std::string out;
  std::string t(8, '\0');
  std::memcpy(t.data(), &timeout_ms, 8);
  if (!client_request(static_cast<Client*>(handle), 2, key, t, &status,
                      &out))
    return -1;
  if (status != 0) return -2;
  if (static_cast<int>(out.size()) > cap) return -3;
  std::memcpy(buf, out.data(), out.size());
  return static_cast<int>(out.size());
}

int64_t tpustore_add(void* handle, const char* key, int64_t delta) {
  uint8_t status;
  std::string out;
  std::string v(8, '\0');
  std::memcpy(v.data(), &delta, 8);
  if (!client_request(static_cast<Client*>(handle), 3, key, v, &status,
                      &out) ||
      status != 0 || out.size() != 8)
    return INT64_MIN;
  int64_t result;
  std::memcpy(&result, out.data(), 8);
  return result;
}

int tpustore_check(void* handle, const char* key) {
  uint8_t status;
  std::string out;
  if (!client_request(static_cast<Client*>(handle), 4, key, "", &status,
                      &out))
    return -1;
  return status == 0 ? 1 : 0;
}

int tpustore_delete(void* handle, const char* key) {
  uint8_t status;
  std::string out;
  if (!client_request(static_cast<Client*>(handle), 5, key, "", &status,
                      &out))
    return -1;
  return 0;
}

}  // extern "C"
