// Threaded synthetic-batch generator — the native data pipeline
// (SURVEY.md §2a "Data loading" row: the reference leans on torch's C++
// DataLoader worker pool; this is the TPU framework's native equivalent,
// feeding the host->device loader without Python-side RNG cost).
//
// Determinism contract mirrors data/datasets.py: every batch is a pure
// function of (seed, step) — counter-based RNG (splitmix64 streams keyed
// by (seed, step, row)), so any worker count / host layout produces the
// identical global batch.
//
// C ABI for ctypes (no pybind11 in this image).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <functional>
#include <thread>
#include <vector>

namespace {

struct SplitMix64 {
  uint64_t state;
  explicit SplitMix64(uint64_t seed) : state(seed) {}
  uint64_t next() {
    uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
  // uniform in [0, 1)
  double uniform() { return (next() >> 11) * 0x1.0p-53; }
  // standard normal (Box-Muller; one value per call, second discarded —
  // simplicity beats the 2x RNG cost here)
  float normal() {
    double u1 = uniform(), u2 = uniform();
    while (u1 <= 1e-300) u1 = uniform();
    return static_cast<float>(std::sqrt(-2.0 * std::log(u1)) *
                              std::cos(2.0 * M_PI * u2));
  }
  uint64_t below(uint64_t n) { return next() % n; }
};

uint64_t mix_key(uint64_t a, uint64_t b, uint64_t c) {
  SplitMix64 m(a * 0x9E3779B97F4A7C15ULL ^ b * 0xC2B2AE3D27D4EB4FULL ^ c);
  return m.next();
}

void parallel_rows(int64_t rows, int threads,
                   const std::function<void(int64_t, int64_t)>& body) {
  if (threads <= 1 || rows < 2) {
    body(0, rows);
    return;
  }
  std::vector<std::thread> pool;
  int64_t chunk = (rows + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min(rows, lo + chunk);
    if (lo >= hi) break;
    pool.emplace_back(body, lo, hi);
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Class templates: (num_classes, elems) i.i.d. N(0,1), keyed by seed only.
void datagen_templates(uint64_t seed, int64_t num_classes, int64_t elems,
                       float* out, int threads) {
  parallel_rows(num_classes, threads, [&](int64_t lo, int64_t hi) {
    for (int64_t c = lo; c < hi; ++c) {
      SplitMix64 rng(mix_key(seed, 0xC1A55ULL, static_cast<uint64_t>(c)));
      float* row = out + c * elems;
      for (int64_t i = 0; i < elems; ++i) row[i] = rng.normal();
    }
  });
}

// Class-conditional images: y ~ uniform(classes), x = template[y] + noise.
void datagen_images(uint64_t seed, uint64_t step, int64_t batch,
                    int64_t elems, int64_t num_classes, float noise,
                    const float* templates, float* out_x, int32_t* out_y,
                    int threads) {
  parallel_rows(batch, threads, [&](int64_t lo, int64_t hi) {
    for (int64_t b = lo; b < hi; ++b) {
      SplitMix64 rng(mix_key(seed, step + 1, static_cast<uint64_t>(b)));
      int32_t y = static_cast<int32_t>(
          rng.below(static_cast<uint64_t>(num_classes)));
      out_y[b] = y;
      const float* tmpl = templates + static_cast<int64_t>(y) * elems;
      float* row = out_x + b * elems;
      for (int64_t i = 0; i < elems; ++i)
        row[i] = tmpl[i] + noise * rng.normal();
    }
  });
}

// LM token stream: noised affine recurrence t[i+1] = (a*t[i] + c) % V
// with noise_frac uniform-random tokens. Writes (batch, seq_len+1)
// int32; the caller slices inputs/targets.
void datagen_lm(uint64_t seed, uint64_t step, int64_t batch,
                int64_t seq_len, int64_t vocab, int64_t a, int64_t c,
                float noise_frac, int32_t* out, int threads) {
  parallel_rows(batch, threads, [&](int64_t lo, int64_t hi) {
    for (int64_t b = lo; b < hi; ++b) {
      SplitMix64 rng(mix_key(seed, step + 1,
                             0x1A11ULL ^ static_cast<uint64_t>(b)));
      int32_t* row = out + b * (seq_len + 1);
      int64_t tok = static_cast<int64_t>(
          rng.below(static_cast<uint64_t>(vocab)));
      row[0] = static_cast<int32_t>(tok);
      for (int64_t i = 0; i < seq_len; ++i) {
        tok = (a * tok + c) % vocab;
        if (rng.uniform() < noise_frac)
          tok = static_cast<int64_t>(
              rng.below(static_cast<uint64_t>(vocab)));
        row[i + 1] = static_cast<int32_t>(tok);
      }
    }
  });
}

}  // extern "C"
